"""Router (fleet front tier) tests: rendezvous stability, prefix-key
alignment, retry-with-failover, Retry-After honoring, outlier
ejection/recovery, SSE zero-token failover and mid-stream terminal
error, drain orchestration, the ``router.upstream`` fault site, and
the PR's serving plumbing (client-disconnect-through-proxy KV
reclamation, ``kv:<model>`` readiness blocker, compile-cache env
wiring).

Most tests run the real :class:`Router` over stdlib fake replicas so
failure timing is scripted exactly; the disconnect-through-proxy
regression uses a real ``GenerationEngine`` + ``ModelServer`` so KV
accounting is the real thing.
"""
import http.client
import json
import socket
import threading
import time
import types
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, telemetry
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import (GenerationEngine, ModelServer,
                                         Router)
from incubator_mxnet_tpu.serving import metrics as smetrics
from incubator_mxnet_tpu.serving import slo as _slo
from incubator_mxnet_tpu.serving.lifecycle import OPEN
from incubator_mxnet_tpu.serving.router import (NoReplicaAvailable,
                                                prefix_key,
                                                rendezvous_order)


@pytest.fixture(autouse=True)
def _clean_state():
    fault.clear_plan()
    telemetry.stop()
    telemetry.reset()
    _slo.tracker.reset()
    yield
    fault.clear_plan()
    telemetry.stop()
    telemetry.reset()
    _slo.tracker.reset()


# ------------------------------------------------------------ fake fleet
class FakeReplica:
    """A scripted stdlib replica: answers ``/readyz``/``/slo`` like
    ``mxtpu-serve`` and plays back per-request plans for ``:predict``
    and ``:generate`` so failure timing is exact."""

    def __init__(self):
        self.ready = True
        self.burn = 0.0
        self.predict_plan = []          # ("ok"|"429"|"503", retry_after)
        self.generate_plan = []         # "ok"|"die_before_first"|"die_midstream"
        self.tokens = [5, 6, 7, 8]
        self.predict_rids = []
        self.generate_rids = []
        self.drains = 0
        self.undrains = 0
        self._srv = None
        self._thread = None
        self.port = None

    @property
    def id(self):
        return f"127.0.0.1:{self.port}"

    def start(self, port=0):
        rep = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code, obj, headers=None):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/readyz":
                    if rep.ready:
                        self._json(200, {"status": "ready",
                                         "draining": False})
                    else:
                        self._json(503, {"status": "unready",
                                         "draining": False})
                elif self.path == "/slo":
                    self._json(200, {"models":
                                     {"g": {"burn_rate": rep.burn}}})
                else:
                    self._json(200, {"models": {}})

            def _chunk(self, data):
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                rid = self.headers.get("X-Request-Id", "")
                if self.path == "/admin/drain":
                    rep.drains += 1
                    rep.ready = False
                    self._json(200, {"draining": True})
                    return
                if self.path == "/admin/undrain":
                    rep.undrains += 1
                    rep.ready = True
                    self._json(200, {"draining": False})
                    return
                if self.path.endswith(":predict"):
                    rep.predict_rids.append(rid)
                    kind, arg = rep.predict_plan.pop(0) \
                        if rep.predict_plan else ("ok", None)
                    if kind == "ok":
                        self._json(200, {"ok": True, "replica": rep.id,
                                         "request_id": rid})
                    elif kind == "429":
                        self._json(429, {"error": "queue full",
                                         "retry_after": arg},
                                   headers={"Retry-After": arg})
                    else:
                        self._json(503, {"error": "shedding"},
                                   headers={"Retry-After": arg or 1})
                    return
                if self.path.endswith(":generate"):
                    rep.generate_rids.append(rid)
                    mode = rep.generate_plan.pop(0) \
                        if rep.generate_plan else "ok"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    self.wfile.flush()
                    if mode == "die_before_first":
                        # shutdown() actually sends the FIN (close()
                        # alone keeps the fd alive via rfile/wfile)
                        self.connection.shutdown(socket.SHUT_RDWR)
                        self.connection.close()     # zero events on wire
                        return
                    for i, t in enumerate(rep.tokens):
                        self._chunk(b"event: token\ndata: "
                                    + json.dumps({"token": t,
                                                  "index": i}).encode()
                                    + b"\n\n")
                        if mode == "die_midstream" and i == 1:
                            self.connection.shutdown(socket.SHUT_RDWR)
                            self.connection.close()
                            return
                    self._chunk(b"event: done\ndata: "
                                + json.dumps(
                                    {"tokens": rep.tokens,
                                     "request_id": rid}).encode()
                                + b"\n\n")
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                    return
                self._json(404, {"error": "?"})

        self._srv = ThreadingHTTPServer(("127.0.0.1", port), H)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None


def _router(reps, **kw):
    kw.setdefault("port", 0)
    kw.setdefault("health_interval", 0.05)
    kw.setdefault("retry_deadline", 5.0)
    specs = [r if isinstance(r, str) else r.id for r in reps]
    return Router(specs, **kw).start()


def _post(port, path, body, headers=None, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=json.dumps(body).encode(),
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    return conn, conn.getresponse()


def _predict(port, headers=None, timeout=10):
    conn, resp = _post(port, "/v1/models/g:predict", {"inputs": [[1]]},
                       headers, timeout)
    out = (resp.status, json.loads(resp.read() or b"{}"),
           {k.lower(): v for k, v in resp.getheaders()})
    conn.close()
    return out


def _read_sse(resp):
    """(tokens, events) from an SSE response stream."""
    toks, events = [], []
    for line in resp:
        line = line.strip()
        if line.startswith(b"event:"):
            events.append(line.split(b":", 1)[1].strip().decode())
        elif line.startswith(b"data:"):
            d = json.loads(line.split(b":", 1)[1])
            if "token" in d:
                toks.append(d["token"])
    return toks, events


# --------------------------------------------------- rendezvous hashing
def test_rendezvous_stability_one_nth_moves():
    ids = [f"replica{i}:80" for i in range(5)]
    keys = [prefix_key(list(range(k, k + 32)), 16, 2)
            for k in range(400)]
    before = {k: rendezvous_order(k, ids)[0] for k in keys}
    after = {k: rendezvous_order(k, ids[:-1])[0] for k in keys}
    # keys owned by the removed replica redistribute; EVERY other key
    # keeps its owner — the ~1/N property that keeps the prefix cache
    # warm through membership churn
    moved = [k for k in keys if before[k] != ids[-1]
             and after[k] != before[k]]
    orphaned = [k for k in keys if before[k] == ids[-1]]
    assert moved == []
    assert 0 < len(orphaned) < len(keys) / 2   # ~1/5 of 400

    # adding a replica moves only the keys the newcomer wins
    grown = {k: rendezvous_order(k, ids + ["replica5:80"])[0]
             for k in keys}
    assert all(grown[k] in (before[k], "replica5:80") for k in keys)


def test_prefix_key_block_alignment():
    bs = 16
    a = prefix_key(list(range(32)) + [99, 98], bs, 2)
    b = prefix_key(list(range(32)) + [1, 2, 3], bs, 2)
    assert a == b                      # same leading 2 blocks → same key
    assert prefix_key(list(range(32)), bs, 2) == a
    c = prefix_key([7] + list(range(1, 32)), bs, 2)
    assert c != a                      # diverges inside the first block
    assert prefix_key(list(range(bs - 1)), bs, 2) is None  # < one block
    # the cap: a third aligned block doesn't change the key
    assert prefix_key(list(range(48)), bs, 2) == a


# ------------------------------------------------------------- failover
def test_predict_failover_keeps_request_id():
    live = FakeReplica().start()
    # a dead port: bind, learn the port, close — nothing listens there
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    router = _router([f"127.0.0.1:{dead_port}", live],
                     retries=3, affinity=False)
    try:
        # the dead replica never becomes ready (health poll fails), so
        # routing already avoids it; force it eligible to prove the
        # REQUEST path fails over too
        dead = router.replica(f"127.0.0.1:{dead_port}")
        failures0 = smetrics.ROUTER_FAILOVERS.value
        for _ in range(4):
            dead.ready = True
            dead.reachable = True
            dead.breaker.record_success()
            status, body, headers = _predict(router.port,
                                             {"x-request-id": "fo-1"})
            assert status == 200 and body["ok"]
            assert body["request_id"] == "fo-1"      # id rode every hop
            assert headers["x-request-id"] == "fo-1"
        assert smetrics.ROUTER_FAILOVERS.value > failures0
        assert all(r == "fo-1" for r in live.predict_rids)
    finally:
        router.stop()
        live.stop()


def test_no_replica_gives_503_with_retry_after():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    router = _router([f"127.0.0.1:{dead_port}"], retries=1,
                     retry_deadline=1.0)
    try:
        status, body, headers = _predict(router.port)
        assert status == 503
        assert body["request_id"]
        assert "retry-after" in headers
        # and the router's own readiness reflects the empty fleet
        r = urllib.request.Request(
            f"http://127.0.0.1:{router.port}/readyz")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(r, timeout=5)
        assert ei.value.code == 503
    finally:
        router.stop()


def test_retry_after_is_honored():
    rep = FakeReplica().start()
    rep.predict_plan = [("429", 0.4), ("ok", None)]
    router = _router([rep], retries=2)
    try:
        t0 = time.monotonic()
        status, body, _ = _predict(router.port)
        elapsed = time.monotonic() - t0
        assert status == 200 and body["ok"]
        assert len(rep.predict_rids) == 2
        # the second attempt waited out the server's hint (no other
        # replica to fail over to)
        assert elapsed >= 0.3
    finally:
        router.stop()
        rep.stop()


def test_429_fails_over_immediately_when_fleet_has_capacity():
    a, b = FakeReplica().start(), FakeReplica().start()
    a.predict_plan = [("429", 5.0)] * 10    # parks a for 5s every time
    router = _router([a, b], retries=3, affinity=False)
    try:
        t0 = time.monotonic()
        for _ in range(4):
            status, body, _ = _predict(router.port)
            assert status == 200
            assert body["replica"] == b.id
        # never slept out the 5s hint: an alternative existed
        assert time.monotonic() - t0 < 2.0
        # and the parked replica is backing off
        assert not router.replica(a.id).eligible() \
            or not a.predict_rids
    finally:
        router.stop()
        a.stop()
        b.stop()


# -------------------------------------------------- ejection / recovery
def test_ejection_and_recovery():
    rep = FakeReplica().start()
    router = Router([rep.id], port=0, health_interval=30,
                    eject_threshold=2, eject_cooldown_seconds=0.1)
    router.check_health_once()
    assert router.replica(rep.id).eligible()
    port = rep.port
    rep.stop()                          # the process dies
    for _ in range(2):
        router.check_health_once()
    r = router.replica(rep.id)
    assert r.breaker.state == OPEN      # ejected
    assert not r.eligible()
    with pytest.raises(NoReplicaAvailable):
        router.route()
    # the replica comes back on the same port; the health loop is the
    # probe — its first success re-admits
    rep2 = FakeReplica()
    rep2.start(port=port)
    try:
        router.check_health_once()
        assert router.replica(rep.id).breaker.state != OPEN
        assert router.replica(rep.id).eligible()
    finally:
        rep2.stop()


# ----------------------------------------------------------------- SSE
def _affine_prompt(router, owner_id, block=16):
    """A prompt whose rendezvous owner (over the router's replica ids)
    is ``owner_id`` — makes multi-replica SSE tests deterministic."""
    ids = [r.id for r in router.replicas]
    for seed in range(200):
        toks = [seed] * (2 * block)
        key = prefix_key(toks, block, 2)
        if rendezvous_order(key, ids)[0] == owner_id:
            return toks
    raise AssertionError("no prompt found for owner")


def test_sse_zero_token_death_fails_over_transparently():
    a, b = FakeReplica().start(), FakeReplica().start()
    a.generate_plan = ["die_before_first"] * 5
    router = _router([a, b], retries=2)
    try:
        toks = _affine_prompt(router, a.id)
        errors0 = smetrics.ROUTER_STREAM_ERRORS.value
        conn, resp = _post(router.port, "/v1/models/g:generate",
                           {"tokens": toks, "stream": True},
                           {"x-request-id": "sse-fo"})
        assert resp.status == 200
        got, events = _read_sse(resp)
        conn.close()
        assert got == b.tokens          # b served it end to end
        assert events[-1] == "done"
        assert "error" not in events    # the death was invisible
        assert a.generate_rids == ["sse-fo"]    # a WAS tried first
        assert smetrics.ROUTER_STREAM_ERRORS.value == errors0
    finally:
        router.stop()
        a.stop()
        b.stop()


def test_sse_midstream_death_is_terminal_error_event():
    rep = FakeReplica().start()
    rep.generate_plan = ["die_midstream"]
    router = _router([rep], retries=2)
    try:
        errors0 = smetrics.ROUTER_STREAM_ERRORS.value
        conn, resp = _post(router.port, "/v1/models/g:generate",
                           {"tokens": [1] * 32, "stream": True},
                           {"x-request-id": "sse-mid"})
        assert resp.status == 200
        toks, events, err = [], [], None
        for line in resp:
            line = line.strip()
            if line.startswith(b"event:"):
                events.append(line.split(b":", 1)[1].strip().decode())
            elif line.startswith(b"data:"):
                d = json.loads(line.split(b":", 1)[1])
                if "token" in d:
                    toks.append(d["token"])
                elif "error" in d:
                    err = d
        conn.close()
        # tokens were on the wire, so no silent hang and no silent
        # replay: a terminal SSE error event carrying the request id
        assert toks == rep.tokens[:2]
        assert events[-1] == "error"
        assert err["request_id"] == "sse-mid"
        assert smetrics.ROUTER_STREAM_ERRORS.value == errors0 + 1
    finally:
        router.stop()
        rep.stop()


# ------------------------------------------------------------- draining
def test_drain_orchestration_zero_downtime():
    a, b = FakeReplica().start(), FakeReplica().start()
    router = _router([a, b], affinity=False)
    try:
        # drain a through the router
        conn, resp = _post(router.port, "/admin/drain",
                           {"replica": a.id})
        out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert out["drained"] is True and out["inflight"] == 0
        assert a.drains == 1            # forwarded to the replica
        n0 = len(a.predict_rids)
        for _ in range(8):
            status, body, _ = _predict(router.port)
            assert status == 200        # zero downtime
            assert body["replica"] == b.id
        assert len(a.predict_rids) == n0    # a got nothing while drained
        # undrain: a takes traffic again
        conn, resp = _post(router.port, "/admin/undrain",
                           {"replica": a.id})
        assert resp.status == 200
        resp.read()
        conn.close()
        assert a.undrains == 1
        assert router.replica(a.id).eligible()
        seen = set()
        for _ in range(16):
            _, body, _ = _predict(router.port)
            seen.add(body["replica"])
        assert seen == {a.id, b.id}
        # unknown replica → 404
        conn, resp = _post(router.port, "/admin/drain",
                           {"replica": "nope:1"})
        assert resp.status == 404
        resp.read()
        conn.close()
    finally:
        router.stop()
        a.stop()
        b.stop()


# ------------------------------------------------------ fault injection
def test_router_upstream_fault_site_drills_failover():
    rep = FakeReplica().start()
    router = _router([rep], retries=2)
    try:
        fault.install_plan("router.upstream:ioerror@1")
        status, body, _ = _predict(router.port)
        assert status == 200 and body["ok"]
        assert fault.site_calls("router.upstream") >= 2
    finally:
        router.stop()
        rep.stop()


# ---------------------------------------------- affinity concentration
def test_affinity_routes_same_prefix_to_one_replica():
    a, b, c = (FakeReplica().start() for _ in range(3))
    router = _router([a, b, c], spill_margin=64)
    try:
        toks = [3] * 32
        for _ in range(9):
            conn, resp = _post(router.port, "/v1/models/g:generate",
                               {"tokens": toks, "max_new_tokens": 2})
            assert resp.status == 200
            resp.read()
            conn.close()
        counts = [len(r.generate_rids) for r in (a, b, c)]
        assert sorted(counts) == [0, 0, 9]  # all on the prefix owner
        # a different prefix may land elsewhere, but stays concentrated
        for _ in range(5):
            conn, resp = _post(router.port, "/v1/models/g:generate",
                               {"tokens": [4] * 32})
            resp.read()
            conn.close()
        counts = sorted(len(r.generate_rids) for r in (a, b, c))
        assert counts[-1] in (9, 14) and sum(counts) == 14
    finally:
        router.stop()
        for r in (a, b, c):
            r.stop()


# ===================================================== PR plumbing
def _tiny_gen_engine(max_slots=2, max_len=64):
    mx.random.seed(3)
    net = GPTModel(vocab_size=50, units=32, hidden_size=64,
                   num_layers=2, num_heads=2, max_length=max_len,
                   dropout=0.0)
    net.initialize(init=mx.init.Normal(0.6))
    net(mx.nd.array(np.zeros((1, 2), np.int32)))
    return GenerationEngine(net, name="g", max_slots=max_slots,
                            max_len=max_len)


def test_client_disconnect_through_router_frees_kv():
    """Satellite regression: an SSE client disconnect THROUGH the proxy
    hop must propagate to the replica as a cancel (``Cancelled``) and
    free the paged KV blocks and slot — no leak behind the router."""
    eng = _tiny_gen_engine(max_len=256)
    srv = ModelServer(port=0)
    srv.add_model("g", eng)
    srv.start()
    router = _router([f"127.0.0.1:{srv.port}"])
    try:
        batcher = srv.get_model("g")
        cancelled0 = smetrics.CANCELLED.value
        conn, resp = _post(router.port, "/v1/models/g:generate",
                           {"tokens": [3, 7, 11],
                            "max_new_tokens": 200, "stream": True},
                           {"x-request-id": "dc-1"})
        assert resp.status == 200
        seen = 0
        for line in resp:
            if line.startswith(b"data:"):
                seen += 1
                if seen >= 2:
                    break
        # Walk away mid-stream.  shutdown() actually puts the FIN on
        # the wire — close() alone defers while resp's buffered reader
        # holds an io-ref on the fd, and the router would never see the
        # disconnect.
        conn.sock.shutdown(socket.SHUT_RDWR)
        conn.sock.close()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if batcher.slots_in_use() == 0 \
                    and smetrics.CANCELLED.value == cancelled0 + 1 \
                    and eng.pool.stats()["kv_blocks_in_use"] == 0:
                break
            time.sleep(0.05)
        assert batcher.slots_in_use() == 0
        assert smetrics.CANCELLED.value == cancelled0 + 1
        assert eng.pool is not None
        assert eng.pool.stats()["kv_blocks_in_use"] == 0  # blocks freed
    finally:
        router.stop()
        srv.stop()


def test_kv_starvation_blocks_readiness(monkeypatch):
    """Satellite: a BlockPool exhausted for K consecutive watchdog
    sweeps surfaces as a ``kv:<model>`` readiness blocker."""
    monkeypatch.setenv("MXNET_SERVE_KV_STARVE_SWEEPS", "3")
    eng = _tiny_gen_engine()
    srv = ModelServer(port=0)
    srv.add_model("g", eng)
    batcher = srv.get_model("g")
    try:
        ready, body = srv.readiness()
        assert ready
        monkeypatch.setattr(
            eng, "pool", types.SimpleNamespace(free_blocks=0,
                                               stats=lambda: {}))
        for _ in range(2):
            batcher.check_worker(0)     # two sweeps: not starved yet
        assert not batcher.kv_starved
        assert srv.readiness()[0]
        batcher.check_worker(0)         # third consecutive sweep
        assert batcher.kv_starved
        ready, body = srv.readiness()
        assert not ready
        assert "kv:g" in body["blockers"]
        assert batcher.stats()["kv_starved"] is True
        # capacity returns → blocker clears on the next sweep
        eng.pool.free_blocks = 5
        batcher.check_worker(0)
        assert not batcher.kv_starved
        assert srv.readiness()[0]
    finally:
        batcher.close()


def test_compile_cache_env_wires_jax_config(monkeypatch, tmp_path):
    """Satellite: ``MXNET_COMPILE_CACHE_DIR`` flips on the JAX
    persistent compilation cache at engine init."""
    import jax

    from incubator_mxnet_tpu.serving import engine as eng_mod

    cache_dir = str(tmp_path / "cc")
    prev = {k: getattr(jax.config, k) for k in
            ("jax_compilation_cache_dir",
             "jax_persistent_cache_min_compile_time_secs",
             "jax_persistent_cache_min_entry_size_bytes")}
    monkeypatch.setattr(eng_mod, "_compile_cache_dir", None)
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", cache_dir)
    try:
        eng_mod.ensure_compile_cache()
        assert jax.config.jax_compilation_cache_dir == cache_dir
        # idempotent — a second engine init must not re-configure
        monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", "/elsewhere")
        eng_mod.ensure_compile_cache()
        assert jax.config.jax_compilation_cache_dir == cache_dir
    finally:
        for k, v in prev.items():
            jax.config.update(k, v)


def test_retry_after_hint_extractor():
    class E(Exception):
        retry_after = 0.25

    assert fault.retry_after_hint(E()) == 0.25
    assert fault.retry_after_hint(ValueError("x")) is None

    class Neg(Exception):
        retry_after = -1.0

    assert fault.retry_after_hint(Neg()) is None

    class Junk(Exception):
        retry_after = "soon"

    assert fault.retry_after_hint(Junk()) is None
