"""Worker script for the multi-process DCN-path tests (reference:
tests/nightly/dist_sync_kvstore.py run under the dmlc trackers).
Launched by tools/launch.py with any worker count N; asserts cross-process
kvstore aggregation and a cross-process SPMDTrainer step against a
single-process oracle, then prints WORKER-<rank>-OK."""
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, parallel
from incubator_mxnet_tpu.gluon import nn

parallel.distributed.initialize()          # DMLC_* env from launch.py
n = jax.process_count()
rank = jax.process_index()

# --- dist_sync kvstore: pushes are summed ACROSS processes --------------
kv = mx.kv.create("dist_sync")
assert kv.num_workers == n and kv.rank == rank
kv.init("w", mx.nd.full((4,), 7.0))
kv.push("w", mx.nd.full((4,), float(rank + 1)))
out = mx.nd.zeros((4,))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), n * (n + 1) / 2.0)

# init adopts rank 0's value everywhere
kv.init("b", mx.nd.full((2,), float(10 + rank)))
out2 = mx.nd.zeros((2,))
kv.pull("b", out=out2)
np.testing.assert_allclose(out2.asnumpy(), 10.0)

# --- SPMDTrainer across processes: n-device global mesh, 1 per process --
mesh = parallel.make_mesh({"data": n})
net = nn.Dense(2, in_units=4)
net.initialize(init=mx.init.One())
net(mx.nd.ones((1, 4)))
tr = parallel.SPMDTrainer(net, gluon.loss.L2Loss(), "sgd",
                          {"learning_rate": 0.1}, mesh=mesh)
per = 4
B = per * n
rng = np.random.default_rng(0)          # same seed: same GLOBAL batch
X_global = rng.standard_normal((B, 4)).astype(np.float32)
y_global = rng.standard_normal((B, 2)).astype(np.float32)
X_local = X_global[rank * per:(rank + 1) * per]
y_local = y_global[rank * per:(rank + 1) * per]
loss = float(tr.step(X_local, y_local))
assert np.isfinite(loss)
tr.sync_to_block()
w = net.weight.data().asnumpy()

# oracle: the same global step computed on ONE process must match
net_ref = nn.Dense(2, in_units=4)
net_ref.initialize(init=mx.init.One())
tr_ref = gluon.Trainer(net_ref.collect_params(), "sgd",
                       {"learning_rate": 0.1})
with mx.autograd.record():
    l = gluon.loss.L2Loss()(net_ref(mx.nd.array(X_global)),
                            mx.nd.array(y_global))
l.backward()
tr_ref.step(B)  # vector-loss backward + step(batch) == SPMD's mean loss
np.testing.assert_allclose(w, net_ref.weight.data().asnumpy(),
                           rtol=1e-5, atol=1e-6)

print(f"WORKER-{rank}-OK", flush=True)
