"""Worker script for the 2-process DCN-path test (reference:
tests/nightly/dist_sync_kvstore.py run under the dmlc 'local' tracker).
Launched by tools/launch.py; asserts cross-process kvstore aggregation and
a cross-process SPMDTrainer step, then prints WORKER-<rank>-OK."""
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, parallel
from incubator_mxnet_tpu.gluon import nn

parallel.distributed.initialize()          # DMLC_* env from launch.py
assert jax.process_count() == 2, jax.process_count()
rank = jax.process_index()

# --- dist_sync kvstore: pushes are summed ACROSS processes --------------
kv = mx.kv.create("dist_sync")
assert kv.num_workers == 2 and kv.rank == rank
kv.init("w", mx.nd.full((4,), 7.0))
kv.push("w", mx.nd.full((4,), float(rank + 1)))
out = mx.nd.zeros((4,))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), 3.0)   # 1 + 2

# init adopts rank 0's value everywhere
kv.init("b", mx.nd.full((2,), float(10 + rank)))
out2 = mx.nd.zeros((2,))
kv.pull("b", out=out2)
np.testing.assert_allclose(out2.asnumpy(), 10.0)

# --- SPMDTrainer across processes: 2-device global mesh, 1 per process --
mesh = parallel.make_mesh({"data": 2})
net = nn.Dense(2, in_units=4)
net.initialize(init=mx.init.One())
net(mx.nd.ones((1, 4)))
tr = parallel.SPMDTrainer(net, gluon.loss.L2Loss(), "sgd",
                          {"learning_rate": 0.1}, mesh=mesh)
rng = np.random.default_rng(0)          # same seed: same GLOBAL batch
X_global = rng.standard_normal((8, 4)).astype(np.float32)
y_global = rng.standard_normal((8, 2)).astype(np.float32)
half = 8 // 2
X_local = X_global[rank * half:(rank + 1) * half]
y_local = y_global[rank * half:(rank + 1) * half]
loss = float(tr.step(X_local, y_local))
assert np.isfinite(loss)
tr.sync_to_block()
w = net.weight.data().asnumpy()

# oracle: the same global step computed locally must match exactly
w0 = np.ones((2, 4), np.float32)
pred = X_global @ w0.T
# L2Loss = mean over batch of 0.5*||p-y||^2 summed over features... use
# autograd on a single process instead of hand-deriving:
net_ref = nn.Dense(2, in_units=4)
net_ref.initialize(init=mx.init.One())
tr_ref = gluon.Trainer(net_ref.collect_params(), "sgd",
                       {"learning_rate": 0.1})
with mx.autograd.record():
    l = gluon.loss.L2Loss()(net_ref(mx.nd.array(X_global)),
                            mx.nd.array(y_global))
l.backward()
tr_ref.step(8)  # vector-loss backward + step(batch) == SPMD's mean loss
np.testing.assert_allclose(w, net_ref.weight.data().asnumpy(),
                           rtol=1e-5, atol=1e-6)

print(f"WORKER-{rank}-OK", flush=True)
