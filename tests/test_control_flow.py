"""Traced control flow: while_loop/cond must lower to lax.while_loop /
lax.cond under a jit trace and match eager numerics (reference:
src/operator/control_flow.cc subgraph ops run inside the graph executor;
tests/python/unittest/test_contrib_control_flow.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.ndarray import contrib
from incubator_mxnet_tpu.ndarray.ndarray import NDArray, from_jax


def _loop_eager(x_np, max_it=6):
    x = mx.nd.array(x_np)
    outs, final = contrib.while_loop(
        cond=lambda s: (s.sum() < 10.0),
        func=lambda s: (s * 2, s + 1),
        loop_vars=[x], max_iterations=max_it)
    return outs.asnumpy(), final[0].asnumpy()


def test_while_loop_traced_matches_eager():
    import jax
    x_np = np.array([1.0, 2.0], np.float32)
    eager_out, eager_final = _loop_eager(x_np)

    def traced(xj):
        outs, final = contrib.while_loop(
            cond=lambda s: (s.sum() < 10.0),
            func=lambda s: (s * 2, s + 1),
            loop_vars=[from_jax(xj)], max_iterations=6)
        return outs._data, final[0]._data

    t_out, t_final = jax.jit(traced)(x_np)
    np.testing.assert_allclose(np.asarray(t_out), eager_out, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(t_final), eager_final, rtol=1e-6)


def test_while_loop_traced_zero_trip():
    """Condition false on entry under trace: zero-padded outputs with the
    static (max_iterations, ...) shape — the traced path knows shapes from
    eval_shape, unlike eager."""
    import jax

    def traced(xj):
        outs, final = contrib.while_loop(
            cond=lambda s: (s.sum() < 0.0),          # false immediately
            func=lambda s: (s * 2, s + 1),
            loop_vars=[from_jax(xj)], max_iterations=4)
        return outs._data, final[0]._data

    x = np.ones((3,), np.float32)
    t_out, t_final = jax.jit(traced)(x)
    assert t_out.shape == (4, 3)
    np.testing.assert_allclose(np.asarray(t_out), np.zeros((4, 3)))
    np.testing.assert_allclose(np.asarray(t_final), x)


def test_while_loop_traced_multi_vars_outputs():
    import jax

    def run(xj, eager):
        i0 = from_jax(xj[0:1]) if not eager else mx.nd.array([0.0])
        s0 = from_jax(xj[1:2]) if not eager else mx.nd.array([1.0])
        outs, finals = contrib.while_loop(
            cond=lambda i, s: (i < 3),
            func=lambda i, s: ([i + s, s * 2], [i + 1, s * 2]),
            loop_vars=[i0, s0], max_iterations=5)
        return [o.asnumpy() if eager else np.asarray(o._data)
                for o in outs], \
               [f.asnumpy() if eager else np.asarray(f._data)
                for f in finals]

    x = np.array([0.0, 1.0], np.float32)
    e_outs, e_finals = run(x, eager=True)

    def traced(xj):
        outs, finals = contrib.while_loop(
            cond=lambda i, s: (i < 3),
            func=lambda i, s: ([i + s, s * 2], [i + 1, s * 2]),
            loop_vars=[from_jax(xj[0:1]), from_jax(xj[1:2])],
            max_iterations=5)
        return tuple(o._data for o in outs) + tuple(f._data for f in finals)

    res = jax.jit(traced)(x)
    for t, e in zip(res[:2], e_outs):
        np.testing.assert_allclose(np.asarray(t), e, rtol=1e-6)
    for t, e in zip(res[2:], e_finals):
        np.testing.assert_allclose(np.asarray(t), e, rtol=1e-6)


def test_while_loop_traced_shape_change_raises():
    import jax

    def traced(xj):
        outs, final = contrib.while_loop(
            cond=lambda s: (s.sum() < 10.0),
            func=lambda s: (s, s.reshape(2, 1)),   # shape change: invalid
            loop_vars=[from_jax(xj)], max_iterations=3)
        return final[0]._data

    with pytest.raises(mx.base.MXNetError):
        jax.jit(traced)(np.ones((2,), np.float32))


def test_cond_traced_matches_eager():
    import jax

    def branchy(x):
        return contrib.cond(
            pred=(x.sum() > 0),
            then_func=lambda: x * 2,
            else_func=lambda: x - 1)

    for sign in (+1.0, -1.0):
        x_np = (sign * np.ones((3,), np.float32))
        eager = branchy(mx.nd.array(x_np)).asnumpy()
        traced = jax.jit(lambda xj: branchy(from_jax(xj))._data)(x_np)
        np.testing.assert_allclose(np.asarray(traced), eager)


def test_cond_traced_multi_output():
    import jax

    def branchy(x):
        return contrib.cond(
            pred=(x.sum() > 0),
            then_func=lambda: [x * 2, x + 1],
            else_func=lambda: [x - 1, x * 3])

    x_np = np.ones((2,), np.float32)
    eager = [o.asnumpy() for o in branchy(mx.nd.array(x_np))]

    def traced(xj):
        outs = branchy(from_jax(xj))
        return tuple(o._data for o in outs)

    res = jax.jit(traced)(x_np)
    for t, e in zip(res, eager):
        np.testing.assert_allclose(np.asarray(t), e)


class _LoopBlock(gluon.HybridBlock):
    """A hybridizable block with a data-dependent loop inside."""

    def hybrid_forward(self, F, x):
        outs, final = F.contrib.while_loop(
            cond=lambda s: (s.sum() < 100.0),
            func=lambda s: (s, s * 2),
            loop_vars=[x], max_iterations=8)
        return final[0]


def test_hybridized_block_with_while_loop():
    """VERDICT r2 item 8 'done' criterion: a hybridized Block containing
    contrib.while_loop produces one compiled program and matches eager."""
    x_np = np.ones((2, 2), np.float32)
    net = _LoopBlock()
    eager = net(mx.nd.array(x_np)).asnumpy()
    net.hybridize()
    hybrid = net(mx.nd.array(x_np)).asnumpy()
    np.testing.assert_allclose(hybrid, eager)
    # second call reuses the cached executable (no retrace) and still works
    hybrid2 = net(mx.nd.array(x_np * 2)).asnumpy()
    eager2 = _LoopBlock()(mx.nd.array(x_np * 2)).asnumpy()
    np.testing.assert_allclose(hybrid2, eager2)
