"""End-to-end training convergence smoke tests (reference model:
tests/python/train/test_mlp.py, test_conv.py — small models must reach an
accuracy threshold in a few epochs)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn


def _synthetic_classification(n=512, dim=16, classes=4, seed=0):
    """Gaussian blobs — linearly separable-ish."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, dim)).astype(np.float32) * 3
    y = rng.integers(0, classes, n)
    X = centers[y] + rng.standard_normal((n, dim)).astype(np.float32)
    return X.astype(np.float32), y.astype(np.float32)


def _accuracy(net, X, y):
    out = net(mx.nd.array(X))
    pred = out.argmax(axis=1).asnumpy()
    return (pred == y).mean()


def test_mlp_convergence():
    X, y = _synthetic_classification()
    ds = gluon.data.ArrayDataset(X, y)
    loader = gluon.data.DataLoader(ds, batch_size=64, shuffle=True)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(5):
        for xb, yb in loader:
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
    assert _accuracy(net, X, y) > 0.9


@pytest.mark.slow
def test_lenet_convergence():
    """LeNet on synthetic 'digit' images: class k = bright kxk corner
    block.  (reference: example/gluon/mnist workalike at toy scale.)"""
    rng = np.random.default_rng(1)
    n, classes = 256, 3
    y = rng.integers(0, classes, n)
    X = rng.standard_normal((n, 1, 12, 12)).astype(np.float32) * 0.3
    for i, c in enumerate(y):
        X[i, 0, : 2 * (c + 1), : 2 * (c + 1)] += 2.0
    y = y.astype(np.float32)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, kernel_size=3, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Conv2D(16, kernel_size=3, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Flatten(),
                nn.Dense(32, activation="relu"),
                nn.Dense(classes))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.003})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    ds = gluon.data.ArrayDataset(X, y)
    loader = gluon.data.DataLoader(ds, batch_size=32, shuffle=True)
    for epoch in range(6):
        for xb, yb in loader:
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
    assert _accuracy(net, X, y) > 0.9


@pytest.mark.slow
def test_lstm_sequence_classification():
    """Sequence task: classify by which half has larger mean."""
    rng = np.random.default_rng(2)
    n, T, C = 256, 8, 4
    X = rng.standard_normal((n, T, C)).astype(np.float32)
    y = (X[:, : T // 2].mean(axis=(1, 2))
         > X[:, T // 2:].mean(axis=(1, 2))).astype(np.float32)

    class Net(nn.HybridSequential):
        pass

    from incubator_mxnet_tpu.gluon import rnn as grnn
    net = nn.HybridSequential()
    with net.name_scope():
        lstm = grnn.LSTM(16, layout="NTC", input_size=C)
        net.add(lstm, nn.HybridLambda(
            lambda F, x: x.slice_axis(1, x.shape[1] - 1,
                                      x.shape[1]).squeeze(axis=1)),
            nn.Dense(2))
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    ds = gluon.data.ArrayDataset(X, y)
    loader = gluon.data.DataLoader(ds, batch_size=64, shuffle=True)
    for epoch in range(8):
        for xb, yb in loader:
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
    assert _accuracy(net, X, y) > 0.8
