"""HybridBlock symbolic tracing + deployment export tests (reference:
HybridBlock.export / SymbolBlock.imports round trip —
tests/python/unittest/test_gluon.py test_export/test_import)."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.block import SymbolBlock
from incubator_mxnet_tpu.symbol.symbol import Symbol


def _convnet():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2), nn.Flatten(), nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.ones((1, 3, 8, 8)))
    return net


def test_to_symbol_traces_graph():
    net = _convnet()
    sym = net.to_symbol("data")
    assert isinstance(sym, Symbol)
    args = sym.list_arguments()
    assert args[0] == "data"
    assert any("weight" in a for a in args)


def test_export_writes_json_and_params(tmp_path):
    net = _convnet()
    net.export(str(tmp_path / "m"), epoch=7)
    assert (tmp_path / "m-symbol.json").is_file()
    assert (tmp_path / "m-0007.params").is_file()


def test_export_import_roundtrip_exact(tmp_path):
    net = _convnet()
    X = mx.nd.array(np.random.default_rng(0).standard_normal(
        (2, 3, 8, 8)).astype(np.float32))
    ref = net(X).asnumpy()
    net.export(str(tmp_path / "m"))
    loaded = SymbolBlock.imports(str(tmp_path / "m-symbol.json"), "data",
                                 str(tmp_path / "m-0000.params"))
    np.testing.assert_array_equal(loaded(X).asnumpy(), ref)


def test_export_with_batchnorm_aux(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.BatchNorm(in_channels=8),
            nn.Dense(2))
    net.initialize(init=mx.init.Xavier())
    X = mx.nd.array(np.random.default_rng(1).standard_normal(
        (4, 4)).astype(np.float32))
    # a few training steps move the running stats off their init
    for _ in range(3):
        with mx.autograd.record():
            loss = (net(X) ** 2).sum()
        loss.backward()
    ref = net(X).asnumpy()                    # inference-mode output
    net.export(str(tmp_path / "bn"))
    loaded = SymbolBlock.imports(str(tmp_path / "bn-symbol.json"), "data",
                                 str(tmp_path / "bn-0000.params"))
    np.testing.assert_allclose(loaded(X).asnumpy(), ref, rtol=1e-5,
                               atol=1e-6)


def test_gluon_to_onnx_pipeline(tmp_path):
    from incubator_mxnet_tpu.contrib import onnx as mxonnx
    net = _convnet()
    X = mx.nd.array(np.random.default_rng(2).standard_normal(
        (2, 3, 8, 8)).astype(np.float32))
    ref = net(X).asnumpy()
    sym = net.to_symbol("data")
    path = mxonnx.export_model(
        sym, {n: p.data() for n, p in net.collect_params().items()},
        [(2, 3, 8, 8)], onnx_file_path=str(tmp_path / "m.onnx"))
    served = mxonnx.import_to_gluon(path)
    np.testing.assert_array_equal(served(X).asnumpy(), ref)


def test_symbolic_dispatch_on_symbol_input():
    net = _convnet()
    import incubator_mxnet_tpu.symbol as S
    out = net(S.var("data"))
    assert isinstance(out, Symbol)
