"""contrib op tests (reference model:
tests/python/unittest/test_contrib_operator.py, test_contrib_control_flow.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd

C = mx.nd.contrib


def test_box_iou():
    a = mx.nd.array([[0, 0, 2, 2], [1, 1, 3, 3]])
    b = mx.nd.array([[0, 0, 2, 2]])
    iou = C.box_iou(a, b).asnumpy()
    onp.testing.assert_allclose(iou[:, 0], [1.0, 1 / 7], rtol=1e-5)


def test_box_nms_suppresses_overlaps():
    dets = mx.nd.array(
        [[[0, .9, 0, 0, 2, 2], [0, .8, 0.1, 0.1, 2, 2], [1, .7, 5, 5, 6, 6]]])
    out = C.box_nms(dets, overlap_thresh=0.5, force_suppress=True).asnumpy()
    scores = out[0, :, 1]
    assert (scores == -1).sum() == 1
    assert .9 in scores and .7 in scores
    # shape is preserved (fixed-size pattern)
    assert out.shape == dets.shape


def test_box_nms_per_class():
    # same boxes, different class ids: no suppression without force
    dets = mx.nd.array([[[0, .9, 0, 0, 2, 2], [1, .8, 0, 0, 2, 2]]])
    out = C.box_nms(dets, overlap_thresh=0.5, id_index=0,
                    force_suppress=False).asnumpy()
    assert (out[0, :, 1] > 0).all()


def test_multibox_pipeline():
    x = mx.nd.zeros((1, 3, 4, 4))
    anchors = C.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1, 2))
    assert anchors.shape == (1, 48, 4)
    label = mx.nd.array([[[0, .1, .1, .4, .4], [-1, 0, 0, 0, 0]]])
    cls_pred = mx.nd.zeros((1, 2, 48))
    loc_t, loc_m, cls_t = C.MultiBoxTarget(anchors, label, cls_pred)
    assert loc_t.shape == (1, 192) and cls_t.shape == (1, 48)
    assert cls_t.asnumpy().max() == 1.0   # gt claims its best anchor
    assert loc_m.asnumpy().sum() > 0
    probs = onp.random.RandomState(0).dirichlet(
        onp.ones(3), size=(1, 48)).transpose(0, 2, 1).astype("float32")
    det = C.MultiBoxDetection(mx.nd.array(probs), mx.nd.zeros((1, 192)),
                              anchors)
    assert det.shape == (1, 48, 6)


def test_roi_align_forward_backward():
    data = mx.nd.array(onp.arange(64, dtype="float32").reshape(1, 1, 8, 8))
    data.attach_grad()
    rois = mx.nd.array([[0, 0, 0, 4, 4]])
    with autograd.record():
        out = C.ROIAlign(data, rois, (2, 2), 1.0)
        s = out.sum()
    s.backward()
    assert out.shape == (1, 1, 2, 2)
    assert float(data.grad.asnumpy().sum()) > 0


def test_bilinear_resize():
    data = mx.nd.array(onp.arange(16, dtype="float32").reshape(1, 1, 4, 4))
    r = C.BilinearResize2D(data, height=8, width=8)
    assert r.shape == (1, 1, 8, 8)
    # corners preserved with align_corners
    assert float(r.asnumpy()[0, 0, 0, 0]) == 0.0
    assert float(r.asnumpy()[0, 0, -1, -1]) == 15.0


def test_bilinear_resize_parity_modes():
    """The reference's size-derivation modes (bilinear_resize.cc):
    odd_scale/like/to_even_*/to_odd_*."""
    d97 = mx.nd.array(onp.zeros((1, 1, 9, 7), "float32"))
    d46 = mx.nd.array(onp.zeros((1, 1, 4, 6), "float32"))

    # odd_scale: even dim -> d*s+1, odd dim -> (d-1)*s+1 (always odd)
    r = C.BilinearResize2D(d46, scale_height=2, scale_width=3,
                           mode="odd_scale")
    assert r.shape == (1, 1, 9, 19)          # 4*2+1, 6*3+1
    r = C.BilinearResize2D(d97, scale_height=2, scale_width=2,
                           mode="odd_scale")
    assert r.shape == (1, 1, 17, 13)         # (9-1)*2+1, (7-1)*2+1

    # like: spatial size of the second input
    r = C.BilinearResize2D(d46, like=d97, mode="like")
    assert r.shape == (1, 1, 9, 7)

    assert C.BilinearResize2D(d97, mode="to_even_down").shape \
        == (1, 1, 8, 6)
    assert C.BilinearResize2D(d97, mode="to_even_up").shape \
        == (1, 1, 10, 8)
    assert C.BilinearResize2D(d46, mode="to_odd_down").shape \
        == (1, 1, 3, 5)
    assert C.BilinearResize2D(d46, mode="to_odd_up").shape \
        == (1, 1, 5, 7)
    # even/odd no-ops keep the size
    assert C.BilinearResize2D(d46, mode="to_even_down").shape \
        == (1, 1, 4, 6)
    assert C.BilinearResize2D(d97, mode="to_odd_up").shape == (1, 1, 9, 7)

    # values: identity-size 'like' must reproduce the input
    src = mx.nd.array(onp.arange(12, dtype="float32").reshape(1, 1, 3, 4))
    same = C.BilinearResize2D(src, like=src, mode="like")
    onp.testing.assert_allclose(same.asnumpy(), src.asnumpy(), rtol=1e-6)

    with pytest.raises(mx.MXNetError, match="mode='like'"):
        C.BilinearResize2D(d46, mode="like")
    with pytest.raises(mx.MXNetError, match="odd_scale"):
        C.BilinearResize2D(d46, mode="odd_scale")
    with pytest.raises(mx.MXNetError, match="unknown mode"):
        C.BilinearResize2D(d46, mode="bogus")


def test_adaptive_avg_pooling():
    data = mx.nd.array(onp.arange(64, dtype="float32").reshape(1, 1, 8, 8))
    ap = C.AdaptiveAvgPooling2D(data, output_size=(2, 2)).asnumpy()
    want = data.asnumpy().reshape(1, 1, 2, 4, 2, 4).mean((3, 5))
    onp.testing.assert_allclose(ap, want, rtol=1e-5)
    # non-divisible output size
    ap3 = C.AdaptiveAvgPooling2D(data, output_size=3)
    assert ap3.shape == (1, 1, 3, 3)


def test_foreach_scan_with_grad():
    xs = mx.nd.array(onp.arange(6, dtype="float32").reshape(3, 2))
    s0 = mx.nd.zeros((2,))
    xs.attach_grad()
    with autograd.record():
        outs, final = C.foreach(lambda x, st: (x * 2 + st, x * 2 + st),
                                xs, s0)
        loss = outs.sum()
    loss.backward()
    assert outs.shape == (3, 2)
    want_final = (onp.arange(6).reshape(3, 2) * 2).cumsum(0)[-1]
    onp.testing.assert_allclose(final.asnumpy(), want_final, rtol=1e-5)
    # d(sum of prefix sums)/dx_i = 2 * (n - i)
    want_grad = 2 * onp.array([[3, 3], [2, 2], [1, 1]], dtype="float32")
    onp.testing.assert_allclose(xs.grad.asnumpy(), want_grad, rtol=1e-5)


def test_foreach_multiple_data_and_states():
    xs = mx.nd.array(onp.ones((4, 2), "float32"))
    ys = mx.nd.array(onp.full((4, 2), 2.0, "float32"))
    s0 = [mx.nd.zeros((2,)), mx.nd.ones((2,))]

    def body(inputs, states):
        x, y = inputs
        a, b = states
        return [x + a, y + b], [a + x, b * 1.0]

    outs, states = C.foreach(body, [xs, ys], s0)
    assert len(outs) == 2 and len(states) == 2
    onp.testing.assert_allclose(states[0].asnumpy(), [4, 4])


def test_while_loop():
    import pytest
    i = mx.nd.array([0.0])
    acc = mx.nd.array([0.0])
    outs, (i_f, acc_f) = C.while_loop(
        lambda i, a: i < 3,
        lambda i, a: ((i.copy(),), (i + 1, a + i)),
        (i, acc), max_iterations=10)
    assert float(i_f.asnumpy()[0]) == 3.0
    assert float(acc_f.asnumpy()[0]) == 3.0   # 0+1+2
    # reference contract: stacked outputs padded to max_iterations
    assert outs.shape == (10, 1)
    onp.testing.assert_allclose(outs.asnumpy()[:3, 0], [0, 1, 2])
    onp.testing.assert_allclose(outs.asnumpy()[3:, 0], 0.0)
    # reference contract: max_iterations is required
    with pytest.raises(ValueError):
        C.while_loop(lambda i: i < 3,
                     lambda i: ((i.copy(),), (i + 1,)), (i,))


def test_while_loop_max_iterations():
    i = mx.nd.array([0.0])
    _, (i_f,) = C.while_loop(lambda i: i < 100,
                             lambda i: ((i.copy(),), (i + 1,)),
                             (i,), max_iterations=5)
    assert float(i_f.asnumpy()[0]) == 5.0


def test_cond():
    r = C.cond(mx.nd.array([1.0]) > 0,
               lambda: mx.nd.ones((2,)),
               lambda: mx.nd.zeros((2,)))
    assert r.asnumpy().sum() == 2
    r2 = C.cond(mx.nd.array([-1.0]) > 0,
                lambda: mx.nd.ones((2,)),
                lambda: mx.nd.zeros((2,)))
    assert r2.asnumpy().sum() == 0


def test_misc_ops():
    assert C.isnan(mx.nd.array([float("nan"), 1.0])).asnumpy().tolist() == \
        [True, False]
    assert C.isinf(mx.nd.array([float("inf"), 1.0])).asnumpy().tolist() == \
        [True, False]
    assert C.isfinite(mx.nd.array([float("inf"), 1.0])).asnumpy().tolist() \
        == [False, True]
    am = C.arange_like(mx.nd.zeros((2, 3)), axis=1)
    assert am.shape == (3,)
    ia = C.index_array(mx.nd.zeros((2, 2)))
    assert ia.shape == (2, 2, 2)
    ic = C.index_copy(mx.nd.zeros((4, 2)),
                      mx.nd.array([1, 3]).astype("int32"),
                      mx.nd.ones((2, 2)))
    assert ic.asnumpy().sum() == 4


def test_bipartite_matching():
    score = mx.nd.array([[[0.9, 0.1], [0.8, 0.2]]])
    rm, cm = C.bipartite_matching(score, threshold=0.5)
    assert rm.shape == (1, 2)
    # greedy: row0 takes col0 (0.9), row1 gets nothing above threshold
    assert float(rm.asnumpy()[0, 0]) == 0.0
    assert float(cm.asnumpy()[0, 0]) == 0.0


def test_arange_like_repeat():
    out = C.arange_like(mx.nd.zeros((2, 3)), repeat=2)
    assert out.shape == (2, 3)
    onp.testing.assert_allclose(out.asnumpy().ravel(),
                                [0, 0, 1, 1, 2, 2])
    out2 = C.arange_like(mx.nd.zeros((2, 3)), axis=1, repeat=2)
    assert out2.shape == (3,)
    onp.testing.assert_allclose(out2.asnumpy(), [0, 0, 1])


def test_multibox_target_negative_mining():
    x = mx.nd.zeros((1, 3, 4, 4))
    anchors = C.MultiBoxPrior(x, sizes=(0.5,), ratios=(1,))
    label = mx.nd.array([[[0, .1, .1, .4, .4]]])
    # confident predictions on the fg class → hard negatives exist
    pred = onp.zeros((1, 3, 16), "float32")
    pred[0, 1] = onp.linspace(0, 1, 16)
    _, _, cls_t = C.MultiBoxTarget(anchors, label, mx.nd.array(pred),
                                   negative_mining_ratio=2.0,
                                   ignore_label=-1.0)
    vals = cls_t.asnumpy()[0]
    assert (vals == -1.0).any()          # unmined negatives ignored
    assert (vals == 0.0).sum() <= 2 * (vals == 1.0).sum() + 1


def test_roialign_position_sensitive_raises():
    with pytest.raises(mx.MXNetError):
        C.ROIAlign(mx.nd.zeros((1, 1, 4, 4)), mx.nd.zeros((1, 5)),
                   (2, 2), 1.0, position_sensitive=True)


def test_symbol_contrib_multi_output():
    import incubator_mxnet_tpu.symbol as sym
    s = sym.contrib.bipartite_matching(sym.var("a"), threshold=0.5)
    ex = s.bind(args={"a": mx.nd.array([[[0.9, 0.1], [0.8, 0.2]]])})
    outs = ex.forward()
    assert len(outs) == 2


def test_symbol_contrib_mirror():
    import incubator_mxnet_tpu.symbol as sym
    a = sym.var("a")
    b = sym.var("b")
    s = sym.contrib.box_iou(a, b)
    ex = s.bind(args={"a": mx.nd.array([[0, 0, 2, 2]]),
                      "b": mx.nd.array([[0, 0, 2, 2]])})
    out = ex.forward()[0]
    onp.testing.assert_allclose(out.asnumpy(), [[1.0]], rtol=1e-5)
    with pytest.raises(AttributeError):
        sym.contrib.foreach
