"""Scanned decode-burst tests (docs/serving.md "Multi-token decode
bursts"): greedy bit-parity of the k-step ``lax.scan`` burst against
per-step decode across k x dense/paged x in-program termination
(EOS-mid-burst, budget-cut-mid-burst), mid-flight join through the
``ContinuousBatcher``, the spec draft-scan, the closed-program-set
contract, and a forced-Pallas parity run."""
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, telemetry
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import ContinuousBatcher, GenerationEngine
from incubator_mxnet_tpu.serving import slo as _slo


@pytest.fixture(autouse=True)
def _clean_state():
    fault.clear_plan()
    telemetry.stop()
    telemetry.reset()
    _slo.tracker.reset()
    yield
    fault.clear_plan()
    telemetry.stop()
    telemetry.reset()
    _slo.tracker.reset()


def _gpt(max_length=64, seed=3):
    mx.random.seed(seed)
    net = GPTModel(vocab_size=50, units=32, hidden_size=64,
                   num_layers=2, num_heads=2, max_length=max_length,
                   dropout=0.0)
    net.initialize(init=mx.init.Normal(0.6))
    net(mx.nd.array(np.zeros((1, 2), np.int32)))
    return net


PROMPTS = ([9, 9, 4, 1], [3, 7, 11], [5, 2])

# per-step continuations are deterministic per (seed, paged) — computed
# once, shared by every k of the parity matrix to keep tier-1 cheap
_REF_CACHE = {}


def _per_step_reference(net, budget=24, max_len=64, paged=False):
    """Ground truth: the per-step host loop, one decode dispatch per
    token, no eos — each slot's full greedy continuation."""
    if paged in _REF_CACHE:
        return _REF_CACHE[paged]
    kw = dict(paged=True, block_size=8) if paged else dict(paged=False)
    eng = GenerationEngine(net, name="ref", max_slots=len(PROMPTS),
                           max_len=max_len, scan_steps=0, **kw)
    outs = [[] for _ in PROMPTS]
    for s, p in enumerate(PROMPTS):
        outs[s].append(eng.prefill(np.asarray(p, np.int32), s,
                                   reserve_tokens=len(p) + budget))
    S = eng.max_slots
    for _ in range(budget - 1):
        last = np.zeros(S, np.int32)
        pos = np.zeros(S, np.int32)
        for s, p in enumerate(PROMPTS):
            last[s] = outs[s][-1]
            pos[s] = len(p) + len(outs[s]) - 1
        nxt = eng.decode(last, pos)
        for s in range(S):
            outs[s].append(int(nxt[s]))
    _REF_CACHE[paged] = outs
    return outs


def _truncate(ref, budget, eos_id):
    """What the serving contract emits from a full greedy continuation
    under a budget and an eos id (eos token itself is emitted)."""
    out = []
    for tok in ref[:budget]:
        out.append(tok)
        if eos_id is not None and tok == eos_id:
            break
    return out


def _run_burst(eng, budgets, eos_ids):
    """Drive decode_burst the way the batcher does: prefill each slot,
    then burst until every slot is done, concatenating each slot's
    emitted prefix."""
    outs = [[] for _ in PROMPTS]
    S = eng.max_slots
    for s, p in enumerate(PROMPTS):
        outs[s].append(eng.prefill(np.asarray(p, np.int32), s,
                                   reserve_tokens=len(p) + budgets[s]))

    def finished(s):
        return len(outs[s]) >= budgets[s] or \
            (eos_ids[s] is not None and outs[s][-1] == eos_ids[s])

    while not all(finished(s) for s in range(S)):
        last = np.zeros(S, np.int32)
        pos = np.zeros(S, np.int32)
        bud = np.ones(S, np.int32)
        eos = np.full(S, -1, np.int32)
        act = np.zeros(S, bool)
        for s, p in enumerate(PROMPTS):
            if finished(s):
                continue
            last[s] = outs[s][-1]
            pos[s] = len(p) + len(outs[s]) - 1
            bud[s] = budgets[s] - len(outs[s])
            if eos_ids[s] is not None:
                eos[s] = eos_ids[s]
            act[s] = True
        toks, emitted = eng.decode_burst(last, pos, bud, eos, act)
        assert toks.shape[0] == eng.scan_steps
        for s in range(S):
            if act[s]:
                assert emitted[s] >= 1   # a live slot always emits
                outs[s].extend(int(t) for t in toks[:emitted[s], s])
            else:
                assert emitted[s] == 0   # free slots emit nothing
    return outs


def _eos_mid_burst(ref, k):
    """Pick an eos id that first occurs strictly mid-burst (index not
    on a k boundary) so the done mask must flip inside the scan."""
    for j, tok in enumerate(ref):
        if j % max(1, k) != max(1, k) - 1 and j > 0 \
                and tok not in ref[:j]:
            return tok
    return ref[1]


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
@pytest.mark.parametrize("k", [1, 4, 8])
def test_burst_parity_matrix(k, paged):
    """k bursts x {dense, paged} x {budget-cut, EOS} mid-burst: every
    emitted token bit-identical to the per-step loop."""
    net = _gpt()
    ref = _per_step_reference(net, paged=paged)
    # slot 0: budget cut NOT on a burst boundary; slot 1: eos that
    # fires mid-burst; slot 2: plain short budget
    budgets = [k + 3 if k > 1 else 3, 24, 10]
    eos_ids = [None, _eos_mid_burst(ref[1], k), None]
    expected = [_truncate(ref[s], budgets[s], eos_ids[s])
                for s in range(len(PROMPTS))]
    assert len(expected[1]) < 24          # the eos really cut slot 1
    kw = dict(paged=True, block_size=8) if paged else dict(paged=False)
    eng = GenerationEngine(net, name=f"scan{k}", scan_steps=k,
                           max_slots=len(PROMPTS), max_len=64, **kw)
    got = _run_burst(eng, budgets, eos_ids)
    assert got == expected
    # lazy compilation stayed inside the closed AOT prediction
    # (warmup-compiles-everything is test_burst_program_joins_closed_set)
    assert eng.compiled_programs() <= eng.expected_programs


@pytest.mark.slow  # tier-1 budget rider: scan program-set closure stays in test_spec_draft_scan_parity_and_program_set
def test_burst_program_joins_closed_set():
    # max_len=16 keeps the prefill bucket ladder (and so the warmup
    # compile bill) minimal — this test only counts programs
    net = _gpt(max_length=16)
    off = GenerationEngine(net, name="off", scan_steps=0, max_slots=1,
                           max_len=16)
    on = GenerationEngine(net, name="on", scan_steps=8, max_slots=1,
                          max_len=16)
    # exactly ONE new program, warmup-compiled, inventoried
    assert on.expected_programs == off.expected_programs + 1
    assert off.warmup() == off.expected_programs
    assert on.warmup() == on.expected_programs
    assert on.program_inventory()["scan_steps"] == 8
    assert off.program_inventory()["scan_steps"] == 0
    with pytest.raises(MXNetError):
        on.scan_steps = 0                 # latched at warmup: a drifted
        on.warmup()                       # prediction must be LOUD
    with pytest.raises(MXNetError):
        GenerationEngine(net, name="bad", scan_steps=-1,
                         max_slots=1, max_len=16)


def test_burst_disabled_rejects_decode_burst():
    eng = GenerationEngine(_gpt(), name="noburst", scan_steps=0,
                           max_slots=2, max_len=64)
    eng.prefill(np.asarray([3, 7, 11], np.int32), 0, reserve_tokens=10)
    with pytest.raises(MXNetError):
        eng.decode_burst(np.zeros(2, np.int32), np.zeros(2, np.int32),
                         np.ones(2, np.int32),
                         np.full(2, -1, np.int32), np.ones(2, bool))


def test_mid_flight_join_burst_identical_to_solo():
    """The batcher's burst gate must not perturb join/leave parity: a
    rider decoding in bursts when a joiner arrives emits exactly its
    solo tokens, and so does the joiner."""
    net = _gpt(max_length=128)
    eng = GenerationEngine(net, name="bj", max_slots=2, max_len=128,
                           scan_steps=8)
    solo_long = eng.generate([9, 9, 4, 1], max_new_tokens=60)
    solo_short = eng.generate([3, 7, 11], max_new_tokens=5)
    eng.reset()
    batcher = ContinuousBatcher(eng, name="bj")
    try:
        req_a = batcher.submit_async([9, 9, 4, 1], max_new_tokens=60)
        while not req_a.tokens_out:
            time.sleep(0.002)
        req_b = batcher.submit_async([3, 7, 11], max_new_tokens=5)
        got_b = req_b.result(timeout=60)
        got_a = req_a.result(timeout=60)
        assert got_a == solo_long
        assert got_b == solo_short
        st = batcher.stats()
        assert st["decode_burst_dispatches"] > 0   # bursts were taken
        assert st["tokens_emitted"] == len(got_a) + len(got_b)
    finally:
        batcher.close()


def test_spec_draft_scan_parity_and_program_set():
    """attach_draft folds the draft's k proposal decodes into one
    scanned dispatch; outputs stay bit-identical to the host-loop
    draft (scan_steps=0 kill switch — spec-vs-plain parity itself is
    test_speculative's), and repeat generates compile nothing new:
    the draft burst is inside the closed program set (the full
    warmup-counts drill is test_burst_program_joins_closed_set)."""
    net = _gpt()
    tgt0 = GenerationEngine(net, name="t0", max_slots=2, max_len=64)
    dr0 = GenerationEngine(net, name="d0", max_slots=2, max_len=64,
                           scan_steps=0)
    tgt0.attach_draft(dr0, spec_k=3)
    assert dr0.scan_steps == 0            # kill switch respected
    host_loop = tgt0.generate([3, 7, 11], max_new_tokens=20,
                              speculative=True)

    tgt1 = GenerationEngine(net, name="t1", max_slots=2, max_len=64)
    dr1 = GenerationEngine(net, name="d1", max_slots=2, max_len=64)
    tgt1.attach_draft(dr1, spec_k=3)
    assert dr1.scan_steps == 3            # draft burst sized to spec_k
    scanned = tgt1.generate([3, 7, 11], max_new_tokens=20,
                            speculative=True)
    assert scanned == host_loop
    n_t, n_d = tgt1.compiled_programs(), dr1.compiled_programs()
    assert n_t <= tgt1.expected_programs
    assert n_d <= dr1.expected_programs
    assert tgt1.generate([3, 7, 11], max_new_tokens=20,
                         speculative=True) == scanned
    assert tgt1.compiled_programs() == n_t
    assert dr1.compiled_programs() == n_d


def test_burst_parity_forced_pallas(monkeypatch):
    """Forced-Pallas run (interpret mode on CPU): the kernel's
    comparison-based position mask honors carry-traced positions."""
    monkeypatch.setenv("MXNET_FA_DECODE_FORCE_PALLAS", "1")
    net = _gpt(max_length=128)           # T=128: tile-aligned
    eng0 = GenerationEngine(net, name="fp0", max_slots=2, max_len=128,
                            scan_steps=0)
    ref = eng0.generate([9, 9, 4, 1], max_new_tokens=12)
    eng = GenerationEngine(net, name="fp", max_slots=2, max_len=128,
                           scan_steps=4)
    out = eng.generate([9, 9, 4, 1], max_new_tokens=12)
    assert out == ref                     # per-step pallas parity
    eng.reset()
    b = ContinuousBatcher(eng, name="fp")
    try:
        assert b.submit([9, 9, 4, 1], max_new_tokens=12) == ref
        assert b.stats()["decode_burst_dispatches"] > 0
    finally:
        b.close()
