"""gluon.data tests (reference model: tests/python/unittest/
test_gluon_data.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon import data as gdata
from incubator_mxnet_tpu.gluon.data.vision import transforms


def test_array_dataset_and_loader():
    X = np.random.randn(10, 3).astype(np.float32)
    y = np.arange(10, dtype=np.float32)
    ds = gdata.ArrayDataset(X, y)
    assert len(ds) == 10
    x0, y0 = ds[0]
    np.testing.assert_allclose(x0, X[0])

    loader = gdata.DataLoader(ds, batch_size=4)
    batches = list(loader)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert xb.shape == (4, 3)
    assert batches[-1][0].shape == (2, 3)


def test_loader_discard_and_shuffle():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    loader = gdata.DataLoader(ds, batch_size=4, last_batch="discard",
                              shuffle=True)
    batches = list(loader)
    assert len(batches) == 2
    seen = np.concatenate([b.asnumpy() for b in batches])
    assert len(set(seen.tolist())) == 8


def test_loader_num_workers():
    ds = gdata.ArrayDataset(np.arange(32, dtype=np.float32))
    loader = gdata.DataLoader(ds, batch_size=8, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    all_vals = sorted(np.concatenate([b.asnumpy() for b in batches]))
    np.testing.assert_allclose(all_vals, np.arange(32))


def test_samplers():
    s = gdata.SequentialSampler(5)
    assert list(s) == [0, 1, 2, 3, 4]
    rs = gdata.RandomSampler(100)
    idx = list(rs)
    assert sorted(idx) == list(range(100))
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "rollover")
    assert list(bs) == [[0, 1, 2], [3, 4, 5]]
    assert list(bs)[0] == [6, 0, 1]  # rolled over


def test_dataset_transform_and_shard():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    ds2 = ds.transform(lambda x: x * 2)
    assert ds2[3] == 6.0
    sh = ds.shard(3, 0)
    assert len(sh) == 4  # 10 = 4+3+3


def test_transforms_totensor_normalize():
    img = mx.nd.array(np.random.randint(0, 255, (8, 6, 3)), dtype=np.uint8)
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 8, 6)
    assert t.dtype == np.float32
    assert float(t.max().asscalar()) <= 1.0
    n = transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.25, 0.5, 1.0))(t)
    assert n.shape == (3, 8, 6)


def test_transforms_resize_crop_flip():
    img = mx.nd.array(np.random.randint(0, 255, (10, 8, 3)),
                      dtype=np.uint8)
    r = transforms.Resize((4, 5))(img)   # (w, h)
    assert r.shape == (5, 4, 3)
    c = transforms.CenterCrop(4)(img)
    assert c.shape == (4, 4, 3)
    rrc = transforms.RandomResizedCrop(6)(img)
    assert rrc.shape == (6, 6, 3)
    f = transforms.RandomFlipLeftRight()(img)
    assert f.shape == img.shape


def test_compose_pipeline():
    aug = transforms.Compose([
        transforms.Resize((8, 8)),
        transforms.ToTensor(),
        transforms.Normalize(0.5, 0.5),
    ])
    img = mx.nd.array(np.random.randint(0, 255, (16, 16, 3)),
                      dtype=np.uint8)
    out = aug(img)
    assert out.shape == (3, 8, 8)


def test_loader_multiprocess_workers_are_processes():
    """num_workers>0 must run dataset access in forked worker processes
    (reference: _MultiWorkerIter), not threads."""
    import os
    parent = os.getpid()

    class PidDataset(gdata.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, idx):
            return np.array([float(os.getpid())], np.float64)

    loader = gdata.DataLoader(PidDataset(), batch_size=4, num_workers=2)
    pids = {int(v) for b in loader for v in b.asnumpy().ravel()}
    assert parent not in pids and len(pids) >= 1


def test_loader_multiprocess_tuple_batches():
    X = np.random.randn(20, 3).astype(np.float32)
    Y = np.arange(20, dtype=np.float32)
    ds = gdata.ArrayDataset(X, Y)
    loader = gdata.DataLoader(ds, batch_size=5, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    xs = np.concatenate([b[0].asnumpy() for b in batches])
    ys = np.concatenate([b[1].asnumpy() for b in batches])
    np.testing.assert_allclose(xs, X)
    np.testing.assert_allclose(ys, Y)


def test_loader_thread_pool_flag():
    ds = gdata.ArrayDataset(np.arange(16, dtype=np.float32))
    loader = gdata.DataLoader(ds, batch_size=4, num_workers=2,
                              thread_pool=True)
    vals = sorted(np.concatenate([b.asnumpy() for b in loader]))
    np.testing.assert_allclose(vals, np.arange(16))


@pytest.mark.skipif((__import__("os").cpu_count() or 1) < 2,
                    reason="needs >1 core to demonstrate parallel decode")
def test_loader_multiprocess_beats_gil():
    """CPU-bound (GIL-holding) per-item work must scale with worker
    processes — the reference's motivation for process workers over
    threads (SURVEY Missing#6)."""
    import time

    class BusyDataset(gdata.Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, idx):
            acc = 0
            for i in range(200_000):   # pure-python: holds the GIL
                acc += i * i
            return np.array([float(acc % 7)], np.float32)

    t0 = time.perf_counter()
    list(gdata.DataLoader(BusyDataset(), batch_size=4, num_workers=0))
    serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    list(gdata.DataLoader(BusyDataset(), batch_size=4, num_workers=4))
    par = time.perf_counter() - t0
    assert par < serial * 0.8, (serial, par)


def test_gluon_utils_download_and_sha1(tmp_path):
    """file:// download + sha1 verification + caching (reference:
    gluon.utils.download/check_sha1)."""
    import hashlib
    from incubator_mxnet_tpu.gluon import utils as gu
    src = tmp_path / "weights.bin"
    src.write_bytes(b"payload")
    h = hashlib.sha1(b"payload").hexdigest()
    out = gu.download(f"file://{src}", path=str(tmp_path / "dl.bin"),
                      sha1_hash=h)
    assert open(out, "rb").read() == b"payload"
    assert gu.check_sha1(out, h)
    # wrong hash raises
    import pytest as _pytest
    import incubator_mxnet_tpu as mx
    with _pytest.raises(mx.MXNetError, match="sha1"):
        gu.download(f"file://{src}", path=str(tmp_path / "dl2.bin"),
                    sha1_hash="0" * 40)
    assert gu.shape_is_known((3, 4))
    assert not gu.shape_is_known((3, -1))
