"""gluon.data tests (reference model: tests/python/unittest/
test_gluon_data.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon import data as gdata
from incubator_mxnet_tpu.gluon.data.vision import transforms


def test_array_dataset_and_loader():
    X = np.random.randn(10, 3).astype(np.float32)
    y = np.arange(10, dtype=np.float32)
    ds = gdata.ArrayDataset(X, y)
    assert len(ds) == 10
    x0, y0 = ds[0]
    np.testing.assert_allclose(x0, X[0])

    loader = gdata.DataLoader(ds, batch_size=4)
    batches = list(loader)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert xb.shape == (4, 3)
    assert batches[-1][0].shape == (2, 3)


def test_loader_discard_and_shuffle():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    loader = gdata.DataLoader(ds, batch_size=4, last_batch="discard",
                              shuffle=True)
    batches = list(loader)
    assert len(batches) == 2
    seen = np.concatenate([b.asnumpy() for b in batches])
    assert len(set(seen.tolist())) == 8


def test_loader_num_workers():
    ds = gdata.ArrayDataset(np.arange(32, dtype=np.float32))
    loader = gdata.DataLoader(ds, batch_size=8, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    all_vals = sorted(np.concatenate([b.asnumpy() for b in batches]))
    np.testing.assert_allclose(all_vals, np.arange(32))


def test_samplers():
    s = gdata.SequentialSampler(5)
    assert list(s) == [0, 1, 2, 3, 4]
    rs = gdata.RandomSampler(100)
    idx = list(rs)
    assert sorted(idx) == list(range(100))
    bs = gdata.BatchSampler(gdata.SequentialSampler(7), 3, "rollover")
    assert list(bs) == [[0, 1, 2], [3, 4, 5]]
    assert list(bs)[0] == [6, 0, 1]  # rolled over


def test_dataset_transform_and_shard():
    ds = gdata.ArrayDataset(np.arange(10, dtype=np.float32))
    ds2 = ds.transform(lambda x: x * 2)
    assert ds2[3] == 6.0
    sh = ds.shard(3, 0)
    assert len(sh) == 4  # 10 = 4+3+3


def test_transforms_totensor_normalize():
    img = mx.nd.array(np.random.randint(0, 255, (8, 6, 3)), dtype=np.uint8)
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 8, 6)
    assert t.dtype == np.float32
    assert float(t.max().asscalar()) <= 1.0
    n = transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.25, 0.5, 1.0))(t)
    assert n.shape == (3, 8, 6)


def test_transforms_resize_crop_flip():
    img = mx.nd.array(np.random.randint(0, 255, (10, 8, 3)),
                      dtype=np.uint8)
    r = transforms.Resize((4, 5))(img)   # (w, h)
    assert r.shape == (5, 4, 3)
    c = transforms.CenterCrop(4)(img)
    assert c.shape == (4, 4, 3)
    rrc = transforms.RandomResizedCrop(6)(img)
    assert rrc.shape == (6, 6, 3)
    f = transforms.RandomFlipLeftRight()(img)
    assert f.shape == img.shape


def test_compose_pipeline():
    aug = transforms.Compose([
        transforms.Resize((8, 8)),
        transforms.ToTensor(),
        transforms.Normalize(0.5, 0.5),
    ])
    img = mx.nd.array(np.random.randint(0, 255, (16, 16, 3)),
                      dtype=np.uint8)
    out = aug(img)
    assert out.shape == (3, 8, 8)
