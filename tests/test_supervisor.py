"""Self-healing fleet tests (docs/robustness.md "Self-healing fleet").

The autoscaling policy, flap breaker, and signal extractors are pure
functions of injected inputs, so the policy surface is enumerated as
tables: hysteresis dead band, cooldown, min/max clamps, below-min
repair beating the cooldown, burn→queue→kv up-pressure precedence, and
the flap breaker's windowed restart budget.  The process-supervision
paths (spawn, health-gated router registration, crash restart,
quarantine, executed scale actions) run against real subprocesses — a
tiny stdlib HTTP fake that answers ``/readyz``/``/healthz`` like
``mxtpu-serve``, so no jax import in the children keeps it fast.  The
full-stack version (real replicas, SSE load, chaos SIGKILLs) is
``ci/run_tests.sh autoscale_smoke``.

Also here: the ``crash`` fault kind (parse, repr, and a real
``os._exit`` in a subprocess) and the router's dynamic-membership
admin API the supervisor builds on.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from incubator_mxnet_tpu import fault, telemetry
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.serving import (AutoscalePolicy, FlapBreaker,
                                         Router, ScaleSignals, Supervisor,
                                         scale_decision)
from incubator_mxnet_tpu.serving import supervisor as sup_mod
from incubator_mxnet_tpu.serving.supervisor import (_fleet_burn,
                                                    _fleet_gauge_sum,
                                                    _kv_utilization)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    fault.clear_plan()
    telemetry.reset()
    yield
    fault.clear_plan()
    telemetry.reset()


# ------------------------------------------------------ policy tables
_POLICY = dict(min_replicas=1, max_replicas=4, burn_up=1.0, burn_down=0.25,
               queue_up=8.0, queue_down=1.0, kv_up=0.85,
               cooldown_seconds=30.0)

# (case, signals-kwargs, want_action, want_target, want_reason)
_DECISION_TABLE = [
    # below-min repair beats everything, cooldown included
    ("below_min_beats_cooldown",
     dict(replicas=0, now=1.0, last_scale_time=0.0), "up", 1, "below_min"),
    # cooldown gates every other opinion, however loud the signals
    ("cooldown_blocks_up",
     dict(replicas=2, burn_rate=5.0, queue_depth=100.0, now=10.0,
          last_scale_time=0.0), "hold", 2, "cooldown"),
    ("cooldown_blocks_down",
     dict(replicas=3, now=29.0, last_scale_time=0.0), "hold", 3,
     "cooldown"),
    ("cooldown_expiry_boundary",
     dict(replicas=2, burn_rate=5.0, now=30.0, last_scale_time=0.0),
     "up", 3, "burn"),
    # up-pressure precedence: burn > queue > kv, reason names the winner
    ("burn_up", dict(replicas=2, burn_rate=1.0, now=100.0), "up", 3,
     "burn"),
    ("burn_beats_queue",
     dict(replicas=2, burn_rate=2.0, queue_depth=1000.0, now=100.0),
     "up", 3, "burn"),
    ("queue_up_is_per_replica",
     dict(replicas=2, queue_depth=16.0, now=100.0), "up", 3, "queue"),
    ("queue_below_per_replica_threshold",
     dict(replicas=4, queue_depth=16.0, now=100.0), "hold", 4, "steady"),
    ("queue_beats_kv",
     dict(replicas=2, queue_depth=16.0, kv_utilization=0.99, now=100.0),
     "up", 3, "queue"),
    ("kv_up", dict(replicas=2, kv_utilization=0.85, now=100.0), "up", 3,
     "kv"),
    # max clamp: pressure at the ceiling degrades to hold, never beyond
    ("at_max_holds",
     dict(replicas=4, burn_rate=9.0, queue_depth=1000.0,
          kv_utilization=1.0, now=100.0), "hold", 4, "at_max"),
    # scale-down wants EVERY signal calm
    ("down_when_all_calm",
     dict(replicas=3, burn_rate=0.25, queue_depth=3.0,
          kv_utilization=0.5, now=100.0), "down", 2, "idle"),
    ("burn_blocks_down",
     dict(replicas=3, burn_rate=0.26, now=100.0), "hold", 3, "steady"),
    ("queue_blocks_down",
     dict(replicas=3, queue_depth=3.1, now=100.0), "hold", 3, "steady"),
    # min clamp: a calm one-replica fleet stays put
    ("min_blocks_down",
     dict(replicas=1, now=100.0), "hold", 1, "steady"),
    # the dead band between the thresholds: hysteresis holds steady
    ("dead_band_burn",
     dict(replicas=2, burn_rate=0.5, now=100.0), "hold", 2, "steady"),
    ("dead_band_queue",
     dict(replicas=2, queue_depth=8.0, now=100.0), "hold", 2, "steady"),
    # one step at a time, whatever the magnitude
    ("one_step_up",
     dict(replicas=1, burn_rate=100.0, queue_depth=1e6, now=100.0),
     "up", 2, "burn"),
]


@pytest.mark.parametrize("case,sig,action,target,reason", _DECISION_TABLE,
                         ids=[row[0] for row in _DECISION_TABLE])
def test_scale_decision_table(case, sig, action, target, reason):
    act = scale_decision(ScaleSignals(**sig), AutoscalePolicy(**_POLICY))
    assert (act.action, act.target, act.reason) == (action, target, reason)


def test_scale_decision_default_policy_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_AUTOSCALE_MAX_REPLICAS", "2")
    monkeypatch.setenv("MXNET_AUTOSCALE_BURN_UP", "0.5")
    act = scale_decision(ScaleSignals(replicas=2, burn_rate=0.5, now=100.0))
    assert (act.action, act.reason) == ("hold", "at_max")


def test_policy_validation():
    with pytest.raises(MXNetError, match="min_replicas"):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(MXNetError, match="max_replicas"):
        AutoscalePolicy(min_replicas=3, max_replicas=2)


# (case, (max_restarts, window), record-times, want-per-record)
_FLAP_TABLE = [
    ("budget_blown_on_excess", (2, 10.0), [0.0, 1.0, 2.0],
     [False, False, True]),
    ("window_prunes_old_events", (2, 10.0), [0.0, 1.0, 20.0, 21.0, 22.0],
     [False, False, False, False, True]),
    ("single_restart_budget", (1, 60.0), [0.0, 5.0], [False, True]),
    ("slow_flap_never_trips", (2, 5.0), [0.0, 10.0, 20.0, 30.0],
     [False, False, False, False]),
]


@pytest.mark.parametrize("case,cfg,times,want", _FLAP_TABLE,
                         ids=[row[0] for row in _FLAP_TABLE])
def test_flap_breaker_table(case, cfg, times, want):
    br = FlapBreaker(max_restarts=cfg[0], window_seconds=cfg[1])
    assert [br.record(t) for t in times] == want


def test_flap_breaker_count_prunes():
    br = FlapBreaker(max_restarts=5, window_seconds=10.0)
    for t in (0.0, 1.0, 2.0):
        br.record(t)
    assert br.count(2.0) == 3
    assert br.count(11.5) == 1          # 0.0 and 1.0 aged out


# --------------------------------------------- signal extraction helpers
def test_fleet_gauge_sum_skips_replica_series():
    state = {"gauges": {"mxtpu_serve_queue_depth": {"values": {
        'model="gen"': 7.0,                       # fleet-merged series
        'model="gen",replica="127.0.0.1:1"': 4.0,  # per-replica duplicate
        'model="gen",replica="127.0.0.1:2"': 3.0,
    }}}}
    assert _fleet_gauge_sum(state, "mxtpu_serve_queue_depth") == 7.0
    assert _fleet_gauge_sum(state, "missing") == 0.0
    assert _fleet_gauge_sum({}, "x") == 0.0


def test_kv_utilization_worst_replica():
    state = {"gauges": {
        "mxtpu_kv_blocks_in_use": {"values": {
            'model="gen",replica="a:1"': 9.0,
            'model="gen",replica="b:2"': 2.0,
            'model="gen"': 11.0,                   # fleet sum: ignored
        }},
        "mxtpu_kv_blocks_total": {"values": {
            'model="gen",replica="a:1"': 10.0,
            'model="gen",replica="b:2"': 10.0,
            'model="gen",replica="c:3"': 0.0,      # zero pool: skipped
            'model="gen"': 20.0,
        }}}}
    assert _kv_utilization(state) == pytest.approx(0.9)
    assert _kv_utilization({}) == 0.0


def test_fleet_burn_worst_model():
    body = {"models": {"gen": {"burn_rate": 0.4},
                       "clf": {"burn_rate": 1.2},
                       "weird": "not-a-dict"}}
    assert _fleet_burn(body) == pytest.approx(1.2)
    assert _fleet_burn({}) == 0.0
    assert _fleet_burn({"models": {}}) == 0.0


# ------------------------------------------------------ crash fault kind
def test_crash_rule_parse_and_repr():
    fault.install_plan("x.y:crash:7@2")
    rules = fault.current_plan().rules["x.y"]
    assert rules[0].kind == "crash" and rules[0].exit_code == 7
    assert "x.y:crash:7@2" in repr(rules[0])
    fault.install_plan("x.y:crash")     # default exit code
    assert (fault.current_plan().rules["x.y"][0].exit_code
            == fault.CRASH_EXIT_CODE)
    with pytest.raises(MXNetError):
        fault.install_plan("x.y:crash:notanint")


def test_crash_kind_hard_exits_subprocess():
    code = ("from incubator_mxnet_tpu import fault\n"
            "fault.install_plan('drill.site:crash:86')\n"
            "try:\n"
            "    fault.inject('drill.site')\n"
            "finally:\n"
            "    print('finally-ran')\n"          # os._exit skips this
            "print('survived')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=_REPO,
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 86, (proc.returncode, proc.stderr[-500:])
    assert "survived" not in proc.stdout
    assert "finally-ran" not in proc.stdout      # a real hard death
    assert "injected crash" in proc.stderr


# ----------------------------------------------- supervised fake fleet
# a stdlib replica: /readyz + /healthz like mxtpu-serve, zero jax import
_FAKE = r"""
import http.server, json, sys
class H(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        body = json.dumps({}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def log_message(self, *a):
        pass
http.server.ThreadingHTTPServer(("127.0.0.1", int(sys.argv[1])),
                                H).serve_forever()
"""
_FAKE_CMD = [sys.executable, "-c", _FAKE, "{port}"]


def _mk_sup(**kw):
    kw.setdefault("replicas", 1)
    kw.setdefault("autoscale", False)
    kw.setdefault("interval_seconds", 0.05)
    kw.setdefault("ready_timeout", 30)
    kw.setdefault("backoff_base", 0.05)
    kw.setdefault("backoff_max", 0.2)
    return Supervisor(_FAKE_CMD, **kw)


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_supervisor_requires_port_placeholder():
    with pytest.raises(MXNetError, match="port"):
        Supervisor([sys.executable, "-c", "pass"])


def test_supervisor_rejects_fleet_above_max():
    with pytest.raises(MXNetError, match="max_replicas"):
        _mk_sup(replicas=5, policy=AutoscalePolicy(max_replicas=4))


def test_supervisor_health_gates_and_restarts():
    """Spawn → /readyz gate → router registration; SIGKILL → restart on
    the SAME port (stable membership), counted as a restart."""
    sup = _mk_sup(max_restarts=5, restart_window_seconds=60)
    try:
        sup.start()
        slot = sup.slots()[0]
        assert slot.state == sup_mod.RUNNING
        router = sup.router
        assert router is not None
        assert router.replica(slot.id).id == slot.id     # registered
        old_pid = slot.pid
        os.kill(slot.pid, signal.SIGKILL)
        _wait(lambda: slot.restarts == 1 and slot.state == sup_mod.RUNNING,
              30, "restart after SIGKILL")
        assert slot.pid != old_pid
        assert slot.id == f"{slot.host}:{slot.port}"     # same identity
        assert router.replica(slot.id).id == slot.id     # still a member
        snap = sup.state()
        assert snap["slots"][0]["restarts"] == 1
        assert snap["alive"] == 1
    finally:
        sup.stop()
    assert not sup.slots()[0].alive()


def test_supervisor_quarantines_flapping_slot():
    sup = _mk_sup(max_restarts=1, restart_window_seconds=60)
    try:
        sup.start()
        slot = sup.slots()[0]
        for kill in range(2):
            # gate on the restart counter, not just RUNNING: the state
            # only flips once the watch loop notices the death
            _wait(lambda k=kill: slot.restarts == k
                  and slot.state == sup_mod.RUNNING, 30,
                  f"slot RUNNING before kill {kill + 1}")
            os.kill(slot.pid, signal.SIGKILL)
        _wait(lambda: slot.state == sup_mod.QUARANTINED, 30, "quarantine")
        with pytest.raises(KeyError):
            sup.router.replica(slot.id)          # removed from the router
        assert sup.active_count() == 0
        # a quarantined corpse stays dead: no respawn on later sweeps
        time.sleep(0.3)
        assert slot.state == sup_mod.QUARANTINED and not slot.alive()
    finally:
        sup.stop()


def test_supervisor_executes_scale_actions_with_drain():
    """Force up/down decisions through injected signals: up spawns and
    health-gates a NEW member; down drains the newest RUNNING member
    out of the router before killing it."""
    events = []
    telemetry.FAULT.subscribe(
        lambda *a, **kw: events.append(kw), passive=True)
    sup = _mk_sup(policy=AutoscalePolicy(min_replicas=1, max_replicas=4,
                                         cooldown_seconds=0.0))

    def force(**sig):
        sig.setdefault("replicas", sup.active_count())
        sig.setdefault("now", time.monotonic())
        sup.collect_signals = lambda: ScaleSignals(**sig)
        return sup.autoscale_once()

    try:
        sup.start()
        act = force(burn_rate=5.0)
        assert (act.action, act.reason) == ("up", "burn")
        assert sup.active_count() == 2
        _wait(lambda: sup.alive_count() == 2, 30, "scale-up member ready")
        second = sup.slots()[1]
        assert sup.router.replica(second.id).id == second.id
        act = force(replicas=2)                  # all calm → down
        assert act.action == "down"
        _wait(lambda: sup.alive_count() == 1, 30, "scale-down executed")
        assert second.state == sup_mod.STOPPED and not second.alive()
        with pytest.raises(KeyError):
            sup.router.replica(second.id)
        drains = [e for e in events
                  if e.get("site") == "router.admin"
                  and e.get("event") == "drain" and e.get("kind") == "begin"]
        assert any(e.get("replica") == second.id for e in drains), \
            "scale-down did not route through the router drain"
        act = force(replicas=1)
        assert (act.action, act.reason) == ("hold", "steady")  # min clamp
    finally:
        sup.stop()


# ------------------------------------------- router dynamic membership
def _get_json(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return json.loads(r.read())


def _fake_member(sup_style_port=0):
    """One bare stdlib fake replica process; returns (proc, 'host:port')."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen([sys.executable, "-c", _FAKE, str(port)])
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=2) as r:
                if r.status == 200:
                    return proc, f"127.0.0.1:{port}"
        except OSError:
            time.sleep(0.05)
    proc.kill()
    raise AssertionError("fake member never became ready")


def test_admin_replicas_join_and_leave_http():
    a_proc, a_id = _fake_member()
    b_proc, b_id = _fake_member()
    router = Router([a_id], port=0, host="127.0.0.1",
                    health_interval=0.05).start()
    try:
        # join
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=10)
        conn.request("POST", "/admin/replicas",
                     body=json.dumps({"replica": b_id}).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200 and out["added"] is True
        assert {r["id"] for r in _get_json(router.port,
                                           "/replicas")["replicas"]} \
            == {a_id, b_id}
        # idempotent re-join
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=10)
        conn.request("POST", "/admin/replicas",
                     body=json.dumps({"replica": b_id}).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200 and out["added"] is False
        # leave (drain-first default)
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=30)
        conn.request("DELETE", f"/admin/replicas/{b_id}?wait_seconds=5")
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200 and out["removed"] is True
        assert out["replica"] == b_id
        assert {r["id"] for r in _get_json(router.port,
                                           "/replicas")["replicas"]} \
            == {a_id}
        # unknown member → 404
        conn = http.client.HTTPConnection("127.0.0.1", router.port,
                                          timeout=10)
        conn.request("DELETE", "/admin/replicas/127.0.0.1:1")
        resp = conn.getresponse()
        resp.read()
        conn.close()
        assert resp.status == 404
        by = telemetry.registry.counter(
            "mxtpu_router_membership_changes").sample()["by"]
        assert by.get("action=join", 0) >= 1
        assert by.get("action=leave", 0) >= 1
    finally:
        router.stop()
        for p in (a_proc, b_proc):
            p.kill()
            p.wait()
