"""Legacy op tail (VERDICT r03 missing #3): SVMOutput, Convolution_v1,
contrib.count_sketch, contrib.PSROIPooling — each against a hand-computed
numpy oracle (reference: src/operator/svm_output.cc, convolution_v1.cc,
contrib/count_sketch.cc, contrib/psroi_pooling.cc)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.ndarray import contrib as C
from incubator_mxnet_tpu.ndarray import nn as N


class TestSVMOutput:
    X = np.array([[2.0, 1.0, -1.0],
                  [0.5, 3.0, 2.8]], np.float32)
    Y = np.array([0, 1], np.float32)

    def _grad(self, use_linear):
        x = mx.nd.array(self.X)
        x.attach_grad()
        with autograd.record():
            out = N.SVMOutput(x, mx.nd.array(self.Y), margin=1.0,
                              regularization_coefficient=0.5,
                              use_linear=use_linear)
        out.backward()
        return out.asnumpy(), x.grad.asnumpy()

    def test_forward_is_identity(self):
        out, _ = self._grad(False)
        np.testing.assert_allclose(out, self.X)

    def test_l2_hinge_gradient(self):
        _, g = self._grad(False)
        # violations l_j = max(0, 1 + x_j - x_y), j != y
        # row 0 (y=0, x_y=2): l = [_, 0, 0]        -> grad 0
        # row 1 (y=1, x_y=3): l = [0, _, 0.8]
        want = np.zeros((2, 3), np.float32)
        want[1, 2] = 2 * 0.5 * 0.8
        want[1, 1] = -2 * 0.5 * 0.8
        np.testing.assert_allclose(g, want, rtol=1e-6)

    def test_l1_hinge_gradient(self):
        _, g = self._grad(True)
        want = np.zeros((2, 3), np.float32)
        want[1, 2] = 0.5          # one active violation
        want[1, 1] = -0.5
        np.testing.assert_allclose(g, want, rtol=1e-6)


def test_convolution_v1_delegates():
    rng = np.random.default_rng(0)
    x = mx.nd.array(rng.standard_normal((2, 3, 8, 8)).astype(np.float32))
    w = mx.nd.array(rng.standard_normal((4, 3, 3, 3)).astype(np.float32))
    b = mx.nd.array(np.zeros(4, np.float32))
    v1 = N.Convolution_v1(x, w, b, kernel=(3, 3), num_filter=4)
    v2 = N.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    np.testing.assert_allclose(v1.asnumpy(), v2.asnumpy(), rtol=1e-5)
    with pytest.raises(mx.MXNetError, match="dilate"):
        N.Convolution_v1(x, w, b, kernel=(3, 3), num_filter=4,
                         dilate=(2, 2))


class TestCountSketch:
    def test_forward_oracle(self):
        rng = np.random.default_rng(1)
        B, D, K = 3, 10, 4
        x = rng.standard_normal((B, D)).astype(np.float32)
        h = rng.integers(0, K, (1, D))
        s = rng.choice([-1.0, 1.0], (1, D)).astype(np.float32)
        out = C.count_sketch(mx.nd.array(x), mx.nd.array(h.astype("int32"),
                                                         dtype="int32"),
                             mx.nd.array(s), out_dim=K).asnumpy()
        want = np.zeros((B, K), np.float32)
        for i in range(D):
            want[:, h[0, i]] += s[0, i] * x[:, i]
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_gradient_is_signed_gather(self):
        rng = np.random.default_rng(2)
        B, D, K = 2, 6, 3
        x = mx.nd.array(rng.standard_normal((B, D)).astype(np.float32))
        h = mx.nd.array(rng.integers(0, K, (1, D)).astype("int32"),
                        dtype="int32")
        s_np = rng.choice([-1.0, 1.0], (1, D)).astype(np.float32)
        x.attach_grad()
        with autograd.record():
            out = C.count_sketch(x, h, mx.nd.array(s_np), out_dim=K)
        out.backward()   # dout = ones -> dx[:, i] = s[i]
        np.testing.assert_allclose(
            x.grad.asnumpy(), np.broadcast_to(s_np, (B, D)), rtol=1e-6)


class TestPSROIPooling:
    def test_oracle(self):
        """output_dim=2, group=2, pooled=2 on a 6x6 map vs numpy loop."""
        rng = np.random.default_rng(3)
        D, g, p = 2, 2, 2
        x = rng.standard_normal((1, D * g * g, 6, 6)).astype(np.float32)
        # incl. half-integer coords: C round() is half-AWAY-from-zero
        # (2.5 -> 3), not banker's rounding
        rois = np.array([[0, 0, 0, 3, 3],
                         [0, 1, 2, 5, 5],
                         [0, 2.5, 0.5, 4.5, 3.5]], np.float32)
        out = C.PSROIPooling(mx.nd.array(x), mx.nd.array(rois),
                             spatial_scale=1.0, output_dim=D,
                             pooled_size=p, group_size=g).asnumpy()

        def cround(v):
            return np.sign(v) * np.floor(np.abs(v) + 0.5)

        def oracle(roi):
            x0 = cround(roi[1]) * 1.0
            y0 = cround(roi[2]) * 1.0
            x1 = cround(roi[3] + 1) * 1.0
            y1 = cround(roi[4] + 1) * 1.0
            rw, rh = max(x1 - x0, 0.1), max(y1 - y0, 0.1)
            res = np.zeros((D, p, p), np.float32)
            for i in range(p):
                ys = int(np.floor(y0 + i * rh / p))
                ye = int(np.ceil(y0 + (i + 1) * rh / p))
                gi = min(i * g // p, g - 1)
                for j in range(p):
                    xs = int(np.floor(x0 + j * rw / p))
                    xe = int(np.ceil(x0 + (j + 1) * rw / p))
                    gj = min(j * g // p, g - 1)
                    for d in range(D):
                        c = (d * g + gi) * g + gj
                        patch = x[0, c, max(ys, 0):max(ye, 0),
                                  max(xs, 0):max(xe, 0)]
                        res[d, i, j] = patch.mean() if patch.size else 0.0
            return res

        for r in range(3):
            np.testing.assert_allclose(out[r], oracle(rois[r]),
                                       rtol=1e-5, atol=1e-6)

    def test_channel_mismatch_raises(self):
        x = mx.nd.array(np.zeros((1, 7, 4, 4), np.float32))
        rois = mx.nd.array(np.array([[0, 0, 0, 2, 2]], np.float32))
        with pytest.raises(mx.MXNetError, match="channels"):
            C.PSROIPooling(x, rois, spatial_scale=1.0, output_dim=2,
                           pooled_size=2)

    def test_gradients_flow(self):
        x = mx.nd.array(np.random.default_rng(4).standard_normal(
            (1, 8, 5, 5)).astype(np.float32))
        rois = mx.nd.array(np.array([[0, 0, 0, 4, 4]], np.float32))
        x.attach_grad()
        with autograd.record():
            out = C.PSROIPooling(x, rois, spatial_scale=1.0, output_dim=2,
                                 pooled_size=2)
            s = out.sum()
        s.backward()
        g = x.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestSymbolicFaces:
    """Optional tensor inputs must survive symbolic graph construction
    (explicit registrations in symbol/op_registry._register_legacy_ops —
    autoregistration can't see defaulted tensor params)."""

    def test_convolution_v1_symbol(self):
        data = mx.sym.Variable("data")
        s = mx.sym.Convolution_v1(data, kernel=(3, 3), num_filter=4,
                                  name="c1")
        assert s.list_arguments() == ["data", "c1_weight", "c1_bias"]
        ex = s.simple_bind(data=(1, 3, 8, 8))
        (out,) = ex.forward(data=mx.nd.zeros((1, 3, 8, 8)))
        assert out.shape == (1, 4, 6, 6)

    def test_crop_symbol_with_like(self):
        data, like = mx.sym.Variable("data"), mx.sym.Variable("like")
        c = mx.sym.Crop(data, like, num_args=2)
        assert c.list_arguments() == ["data", "like"]
        ex = c.bind(args={"data": mx.nd.zeros((1, 1, 6, 6)),
                          "like": mx.nd.zeros((1, 1, 3, 4))})
        (o,) = ex.forward()
        assert o.shape == (1, 1, 3, 4)

    def test_bilinear_resize_symbol_like(self):
        data, like = mx.sym.Variable("data"), mx.sym.Variable("like")
        b = mx.sym.contrib.BilinearResize2D(data, like, mode="like")
        assert b.list_arguments() == ["data", "like"]
        ex = b.bind(args={"data": mx.nd.zeros((1, 1, 4, 4)),
                          "like": mx.nd.zeros((1, 1, 7, 5))})
        (o,) = ex.forward()
        assert o.shape == (1, 1, 7, 5)

    def test_svm_output_symbol(self):
        data, lab = mx.sym.Variable("data"), mx.sym.Variable("label")
        sv = mx.sym.SVMOutput(data, lab)
        assert sv.list_arguments() == ["data", "label"]


class TestCrop:
    def test_offset_and_center_and_like(self):
        x = mx.nd.array(np.arange(2 * 1 * 6 * 8, dtype="float32")
                        .reshape(2, 1, 6, 8))
        from incubator_mxnet_tpu.ndarray.ops import Crop
        o = Crop(x, h_w=(2, 3), offset=(1, 2))
        np.testing.assert_allclose(o.asnumpy(),
                                   x.asnumpy()[:, :, 1:3, 2:5])
        c = Crop(x, h_w=(4, 4), center_crop=True)
        np.testing.assert_allclose(c.asnumpy(),
                                   x.asnumpy()[:, :, 1:5, 2:6])
        ref = mx.nd.zeros((1, 1, 3, 5))
        l = Crop(x, crop_like=ref)
        assert l.shape == (2, 1, 3, 5)

    def test_bad_args_raise(self):
        from incubator_mxnet_tpu.ndarray.ops import Crop
        x = mx.nd.zeros((1, 1, 4, 4))
        with pytest.raises(mx.MXNetError, match="h_w"):
            Crop(x)
        with pytest.raises(mx.MXNetError, match="exceeds"):
            Crop(x, h_w=(5, 2))
        with pytest.raises(mx.MXNetError, match="leaves"):
            Crop(x, h_w=(3, 3), offset=(2, 2))
        with pytest.raises(mx.MXNetError, match="leaves"):
            Crop(x, h_w=(2, 2), offset=(-1, 0))   # no silent wrap-around
