"""Tests for the operator-corpus extensions (reference models:
tests/python/unittest/test_operator.py sections for la_op, sample ops,
spatial transformer, bilinear sampler, roi pooling, correlation, lrn,
matrix ops, contrib fft)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd as ag

nd = mx.nd


class TestLinalg:
    def test_gemm(self):
        rng = np.random.RandomState(0)
        a = rng.randn(2, 3, 4).astype(np.float32)
        b = rng.randn(2, 4, 5).astype(np.float32)
        c = rng.randn(2, 3, 5).astype(np.float32)
        out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                             alpha=2.0, beta=0.5)
        np.testing.assert_allclose(out.asnumpy(), 2 * (a @ b) + 0.5 * c,
                                   rtol=1e-5, atol=1e-5)

    def test_gemm_transpose(self):
        rng = np.random.RandomState(0)
        a = rng.randn(4, 3).astype(np.float32)
        b = rng.randn(4, 5).astype(np.float32)
        c = np.zeros((3, 5), np.float32)
        out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                             transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), a.T @ b, rtol=1e-5,
                                   atol=1e-5)

    def test_potrf_potri(self):
        rng = np.random.RandomState(0)
        m = rng.randn(4, 4).astype(np.float32)
        spd = m @ m.T + 4 * np.eye(4, dtype=np.float32)
        L = nd.linalg_potrf(nd.array(spd))
        np.testing.assert_allclose(
            (L.asnumpy() @ L.asnumpy().T), spd, rtol=1e-4, atol=1e-4)
        inv = nd.linalg_potri(L)
        np.testing.assert_allclose(inv.asnumpy(), np.linalg.inv(spd),
                                   rtol=1e-3, atol=1e-3)

    def test_trsm(self):
        rng = np.random.RandomState(0)
        L = np.tril(rng.randn(4, 4)).astype(np.float32) \
            + 3 * np.eye(4, dtype=np.float32)
        b = rng.randn(4, 2).astype(np.float32)
        x = nd.linalg_trsm(nd.array(L), nd.array(b))
        np.testing.assert_allclose(L @ x.asnumpy(), b, rtol=1e-4,
                                   atol=1e-4)

    def test_trmm_syrk(self):
        rng = np.random.RandomState(0)
        a = rng.randn(3, 3).astype(np.float32)
        b = rng.randn(3, 3).astype(np.float32)
        out = nd.linalg_trmm(nd.array(a), nd.array(b))
        np.testing.assert_allclose(out.asnumpy(), np.tril(a) @ b,
                                   rtol=1e-5, atol=1e-5)
        s = nd.linalg_syrk(nd.array(a), alpha=1.5)
        np.testing.assert_allclose(s.asnumpy(), 1.5 * (a @ a.T),
                                   rtol=1e-5, atol=1e-5)

    def test_det_slogdet_inverse(self):
        rng = np.random.RandomState(0)
        a = rng.randn(3, 3).astype(np.float32) + 2 * np.eye(3,
                                                            dtype=np.float32)
        assert nd.linalg_det(nd.array(a)).asnumpy() == pytest.approx(
            np.linalg.det(a), rel=1e-4)
        sign, logabs = nd.linalg_slogdet(nd.array(a))
        es, el = np.linalg.slogdet(a)
        assert sign.asnumpy() == pytest.approx(es)
        assert logabs.asnumpy() == pytest.approx(el, rel=1e-4)
        np.testing.assert_allclose(
            nd.linalg_inverse(nd.array(a)).asnumpy(), np.linalg.inv(a),
            rtol=1e-4, atol=1e-4)

    def test_diag_roundtrip(self):
        rng = np.random.RandomState(0)
        a = rng.randn(2, 4, 4).astype(np.float32)
        d = nd.linalg_extractdiag(nd.array(a))
        np.testing.assert_allclose(
            d.asnumpy(), np.diagonal(a, axis1=-2, axis2=-1))
        m = nd.linalg_makediag(d)
        np.testing.assert_allclose(
            np.diagonal(m.asnumpy(), axis1=-2, axis2=-1), d.asnumpy())

    def test_trian_roundtrip(self):
        rng = np.random.RandomState(0)
        a = rng.randn(4, 4).astype(np.float32)
        v = nd.linalg_extracttrian(nd.array(a))
        assert v.shape == (10,)
        back = nd.linalg_maketrian(v)
        np.testing.assert_allclose(back.asnumpy(), np.tril(a), rtol=1e-6)

    def test_trian_offset_selects_band(self):
        """offset>0 extracts the strict upper triangle (reference
        semantics; regression: offset sign was ignored)."""
        rng = np.random.RandomState(0)
        a = rng.randn(4, 4).astype(np.float32)
        v = nd.linalg_extracttrian(nd.array(a), offset=1)
        assert v.shape == (6,)
        rows, cols = np.triu_indices(4, k=1)
        np.testing.assert_allclose(v.asnumpy(), a[rows, cols])
        back = nd.linalg_maketrian(v, offset=1)
        assert back.shape == (4, 4)
        np.testing.assert_allclose(back.asnumpy(),
                                   np.triu(a, k=1), rtol=1e-6)

    def test_gemm_axis_param(self):
        rng = np.random.RandomState(0)
        # row axis relocated to axis 0: (3, B, 4) x (4, B, 5) -> (3, B, 5)
        a = rng.randn(3, 2, 4).astype(np.float32)
        b = rng.randn(4, 2, 5).astype(np.float32)
        c = np.zeros((3, 2, 5), np.float32)
        out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                             axis=0).asnumpy()
        ref = np.einsum("ibk,kbj->ibj", a, b)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_potrf_gradient_flows(self):
        m = np.array([[4.0, 1.0], [1.0, 3.0]], np.float32)
        x = nd.array(m)
        x.attach_grad()
        with ag.record():
            y = nd.linalg_potrf(x).sum()
        y.backward()
        assert np.abs(x.grad.asnumpy()).sum() > 0


class TestSamplers:
    def test_sample_shapes_and_ranges(self):
        mx.random.seed(0)
        low = nd.array(np.array([0.0, 10.0], np.float32))
        high = nd.array(np.array([1.0, 20.0], np.float32))
        s = nd.sample_uniform(low, high, shape=(1000,))
        assert s.shape == (2, 1000)
        a = s.asnumpy()
        assert (a[0] >= 0).all() and (a[0] <= 1).all()
        assert (a[1] >= 10).all() and (a[1] <= 20).all()

    def test_sample_normal_moments(self):
        mx.random.seed(0)
        mu = nd.array(np.array([0.0, 5.0], np.float32))
        sig = nd.array(np.array([1.0, 0.1], np.float32))
        s = nd.sample_normal(mu, sig, shape=(4000,)).asnumpy()
        assert abs(s[0].mean()) < 0.1
        assert abs(s[1].mean() - 5.0) < 0.05
        assert abs(s[0].std() - 1.0) < 0.1

    def test_sample_gamma_exponential_poisson(self):
        mx.random.seed(0)
        al = nd.array(np.array([2.0], np.float32))
        be = nd.array(np.array([3.0], np.float32))
        g = nd.sample_gamma(al, be, shape=(4000,)).asnumpy()
        assert abs(g.mean() - 6.0) < 0.5          # E = alpha*beta
        lam = nd.array(np.array([4.0], np.float32))
        e = nd.sample_exponential(lam, shape=(4000,)).asnumpy()
        assert abs(e.mean() - 0.25) < 0.05
        p = nd.sample_poisson(lam, shape=(4000,)).asnumpy()
        assert abs(p.mean() - 4.0) < 0.3

    def test_sample_negative_binomial(self):
        mx.random.seed(0)
        k = nd.array(np.array([5.0], np.float32))
        p = nd.array(np.array([0.5], np.float32))
        s = nd.sample_negative_binomial(k, p, shape=(4000,)).asnumpy()
        assert abs(s.mean() - 5.0) < 0.5          # E = k(1-p)/p


class TestSpatial:
    def test_bilinear_sampler_identity(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 2, 5, 7).astype(np.float32)
        gy, gx = np.meshgrid(np.linspace(-1, 1, 5),
                             np.linspace(-1, 1, 7), indexing="ij")
        grid = np.stack([gx, gy], 0)[None].astype(np.float32)
        out = nd.BilinearSampler(nd.array(x), nd.array(grid))
        np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-5,
                                   atol=1e-5)

    def test_spatial_transformer_identity_affine(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 6, 6).astype(np.float32)
        theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
        out = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                                    target_shape=(6, 6))
        np.testing.assert_allclose(out.asnumpy(), x, rtol=1e-4,
                                   atol=1e-4)

    def test_spatial_transformer_shift(self):
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 1, 1] = 1.0
        # translate by one pixel right+down (normalized: 2/(n-1))
        t = 2.0 / 3.0
        theta = np.array([[1, 0, -t, 0, 1, -t]], np.float32)
        out = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                                    target_shape=(4, 4)).asnumpy()
        assert out[0, 0, 2, 2] == pytest.approx(1.0, abs=1e-5)

    def test_grid_generator_warp(self):
        flow = np.zeros((1, 2, 4, 4), np.float32)   # zero flow = identity
        grid = nd.GridGenerator(nd.array(flow), "warp").asnumpy()
        assert grid.shape == (1, 2, 4, 4)
        np.testing.assert_allclose(grid[0, 0, :, 0], -1.0)
        np.testing.assert_allclose(grid[0, 0, :, -1], 1.0)

    def test_roi_pooling(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0, 0, 0, 3, 3]], np.float32)
        out = nd.ROIPooling(nd.array(x), nd.array(rois), (2, 2),
                            1.0).asnumpy()
        np.testing.assert_allclose(out[0, 0],
                                   [[5.0, 7.0], [13.0, 15.0]])

    def test_roi_pooling_overlapping_bins(self):
        """ROI height 3 pooled to 2: boundary row contributes to BOTH
        bins (reference ceil/floor bin edges; regression: each pixel
        once landed in exactly one bin)."""
        x = np.zeros((1, 1, 4, 4), np.float32)
        x[0, 0, 1, :] = 9.0                # max sits on the shared row
        rois = np.array([[0, 0, 0, 3, 2]], np.float32)  # rows 0..2
        out = nd.ROIPooling(nd.array(x), nd.array(rois), (2, 2),
                            1.0).asnumpy()
        # bin 0 covers rows {0,1}, bin 1 rows {1,2}: both see the 9
        assert out[0, 0, 0, 0] == pytest.approx(9.0)
        assert out[0, 0, 1, 0] == pytest.approx(9.0)

    def test_correlation_self_is_meansquare(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 3, 5, 5).astype(np.float32)
        out = nd.Correlation(nd.array(x), nd.array(x),
                             max_displacement=1).asnumpy()
        assert out.shape == (1, 9, 5, 5)
        center = out[0, 4]                 # zero displacement plane
        np.testing.assert_allclose(center, (x[0] ** 2).mean(0),
                                   rtol=1e-5, atol=1e-5)

    def test_correlation_displacement_orientation(self):
        """Reference: channel (dy,dx) pairs a(y,x) with b(y+dy,x+dx) —
        a rightward-shifted copy peaks in the dx=+1 plane (regression:
        planes were mirrored)."""
        x = np.zeros((1, 1, 5, 5), np.float32)
        x[0, 0, 2, 2] = 1.0
        y = np.roll(x, 1, axis=3)          # y(r, c) = x(r, c-1)
        out = nd.Correlation(nd.array(x), nd.array(y),
                             max_displacement=1).asnumpy()[0]
        # planes ordered dy-major: (dy,dx)=(0,+1) is index 5
        assert out[5, 2, 2] == pytest.approx(1.0)
        assert out[3, 2, 2] == pytest.approx(0.0)   # (0,-1) empty

    def test_correlation_subtract_mode_positive(self):
        a = nd.array(np.zeros((1, 1, 3, 3), np.float32))
        b = nd.array(np.ones((1, 1, 3, 3), np.float32))
        out = nd.Correlation(a, b, max_displacement=0,
                             is_multiply=False).asnumpy()
        np.testing.assert_allclose(out[0, 0], 1.0)

    def test_correlation_unsupported_config_raises(self):
        a = nd.array(np.zeros((1, 1, 3, 3), np.float32))
        with pytest.raises(mx.MXNetError, match="Correlation"):
            nd.Correlation(a, a, kernel_size=3)

    def test_deformable_conv_zero_offset_is_conv(self):
        """With zero offsets, deformable conv must equal ordinary
        convolution (the defining property; reference:
        test_contrib_operator.py deformable tests)."""
        import jax
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 6, 6).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32)
        off = np.zeros((2, 2 * 9, 6, 6), np.float32)
        out = nd.DeformableConvolution(
            nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
            pad=(1, 1)).asnumpy()
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), ((1, 1), (1, 1)))
        np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4,
                                   atol=1e-4)

    def test_deformable_conv_integer_offset_shifts(self):
        """A uniform integer offset equals convolving a shifted input
        (interior pixels)."""
        import jax
        rng = np.random.RandomState(0)
        x = rng.randn(1, 2, 8, 8).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 2 * 9, 8, 8), np.float32)
        off[:, 0::2] = 1.0                 # dy=+1 for every tap
        out = nd.DeformableConvolution(
            nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
            pad=(1, 1)).asnumpy()
        shifted = np.roll(x, -1, axis=2)
        ref = np.asarray(jax.lax.conv_general_dilated(
            shifted, w, (1, 1), ((1, 1), (1, 1))))
        np.testing.assert_allclose(out[:, :, 2:-2, 2:-2],
                                   ref[:, :, 2:-2, 2:-2], rtol=1e-4,
                                   atol=1e-4)

    def test_deformable_conv_grads(self):
        rng = np.random.RandomState(0)
        x = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
        w = nd.array(rng.randn(2, 2, 3, 3).astype(np.float32))
        off = nd.array(rng.randn(1, 18, 5, 5).astype(np.float32) * 0.1)
        for t in (x, w, off):
            t.attach_grad()
        with ag.record():
            y = nd.DeformableConvolution(x, off, w, kernel=(3, 3),
                                         pad=(1, 1)).sum()
        y.backward()
        for t in (x, w, off):
            g = t.grad.asnumpy()
            assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_lrn_matches_formula(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 6, 3, 3).astype(np.float32)
        alpha, beta, k, n = 1e-3, 0.75, 2.0, 5
        out = nd.LRN(nd.array(x), alpha=alpha, beta=beta, knorm=k,
                     nsize=n).asnumpy()
        ref = np.empty_like(x)
        half = n // 2
        for c in range(6):
            lo, hi = max(0, c - half), min(6, c + half + 1)
            acc = (x[:, lo:hi] ** 2).sum(axis=1)
            ref[:, c] = x[:, c] / (k + alpha / n * acc) ** beta
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestTensorOdds:
    def test_depth_space_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 8, 3, 5).astype(np.float32)
        d = nd.depth_to_space(nd.array(x), 2)
        assert d.shape == (2, 2, 6, 10)
        back = nd.space_to_depth(d, 2)
        np.testing.assert_allclose(back.asnumpy(), x)

    def test_unravel_ravel(self):
        idx = nd.array(np.array([0, 5, 11], np.float32))
        un = nd.unravel_index(idx, (3, 4)).asnumpy()
        np.testing.assert_array_equal(un, [[0, 1, 2], [0, 1, 3]])
        back = nd.ravel_multi_index(nd.array(un), (3, 4)).asnumpy()
        np.testing.assert_array_equal(back, [0, 5, 11])

    def test_logsumexp_cumprod_trace(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            nd.logsumexp(nd.array(x), axis=1).asnumpy(),
            np.log(np.exp(x).sum(1)), rtol=1e-5)
        np.testing.assert_allclose(
            nd.cumprod(nd.array(x), axis=1).asnumpy(),
            np.cumprod(x, axis=1), rtol=1e-5)
        sq = rng.randn(4, 4).astype(np.float32)
        assert nd.trace(nd.array(sq)).asnumpy() == pytest.approx(
            np.trace(sq), rel=1e-5)

    def test_hard_sigmoid(self):
        x = nd.array(np.array([-10.0, 0.0, 10.0], np.float32))
        np.testing.assert_allclose(nd.hard_sigmoid(x).asnumpy(),
                                   [0.0, 0.5, 1.0])

    def test_multi_all_finite(self):
        a = nd.array(np.ones((2, 2), np.float32))
        b = nd.array(np.array([1.0, np.inf], np.float32))
        assert nd.multi_all_finite(a).asnumpy()[0] == 1.0
        assert nd.multi_all_finite(a, b).asnumpy()[0] == 0.0

    def test_im2col_col2im(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1, 2, 4, 4).astype(np.float32)
        cols = nd.im2col(nd.array(x), (2, 2), stride=(1, 1))
        assert cols.shape == (1, 8, 9)
        # col2im is the adjoint: ones-cols scatter counts patch coverage
        ones = nd.array(np.ones((1, 8, 9), np.float32))
        img = nd.col2im(ones, (4, 4), (2, 2), stride=(1, 1)).asnumpy()
        # center pixels are covered by 4 patches per channel
        assert img[0, 0, 1, 1] == pytest.approx(4.0)
        assert img[0, 0, 0, 0] == pytest.approx(1.0)

    def test_fft_ifft_roundtrip(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3, 8).astype(np.float32)
        f = nd.fft(nd.array(x))
        assert f.shape == (3, 16)
        back = nd.ifft(f).asnumpy()
        # reference (cuFFT) semantics: unnormalized inverse -> x * d
        np.testing.assert_allclose(back, x * 8, rtol=1e-4, atol=1e-4)

    def test_grads_flow_through_ext_ops(self):
        rng = np.random.RandomState(0)
        x = nd.array(rng.randn(2, 8, 4, 4).astype(np.float32))
        x.attach_grad()
        with ag.record():
            y = nd.depth_to_space(x, 2)
            z = nd.logsumexp(y)
        z.backward()
        assert np.isfinite(x.grad.asnumpy()).all()
        assert np.abs(x.grad.asnumpy()).sum() > 0


class TestFlatParityOps:
    def test_moments(self):
        x = nd.array(np.arange(6.0).reshape(2, 3))
        m, v = nd.moments(x, axes=1)
        np.testing.assert_allclose(m.asnumpy(), [1.0, 4.0])
        np.testing.assert_allclose(v.asnumpy(), [2.0 / 3] * 2, rtol=1e-6)
        m2, v2 = nd.moments(x)
        assert m2.asnumpy() == pytest.approx(2.5)

    def test_softmin_is_softmax_of_negation(self):
        x = nd.array(np.array([[1.0, 2.0, 3.0]], np.float32))
        out = nd.softmin(x).asnumpy()
        ref = np.exp(-x.asnumpy())
        ref /= ref.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_argwhere(self):
        x = nd.array(np.array([[0, 1], [2, 0]], np.float32))
        np.testing.assert_array_equal(nd.argwhere(x).asnumpy(),
                                      [[0, 1], [1, 0]])

    def test_crop_alias(self):
        x = nd.array(np.arange(9.0).reshape(3, 3))
        out = nd.crop(x, begin=(1, 0), end=(3, 2))
        np.testing.assert_allclose(out.asnumpy(), [[3, 4], [6, 7]])

    def test_cast_storage_roundtrip(self):
        x = nd.array(np.eye(3, dtype=np.float32))
        csr = nd.cast_storage(x, "csr")
        assert csr.stype == "csr"
        np.testing.assert_allclose(
            nd.cast_storage(csr, "default").asnumpy(), np.eye(3))

    def test_normal_alias_seeded(self):
        mx.random.seed(5)
        a = nd.normal(shape=(4,)).asnumpy()
        mx.random.seed(5)
        b = nd.normal(shape=(4,)).asnumpy()
        np.testing.assert_array_equal(a, b)

    def test_crop_step_and_bad_kwargs(self):
        x = nd.array(np.arange(9.0).reshape(3, 3))
        out = nd.crop(x, begin=(0, 0), end=(3, 3), step=(2, 2))
        np.testing.assert_allclose(out.asnumpy(), [[0, 2], [6, 8]])
        with pytest.raises(mx.MXNetError, match="unsupported"):
            nd.crop(x, begin=(0, 0), end=(2, 2), bogus=1)

    def test_cast_storage_never_aliases(self):
        x = nd.array(np.ones((2, 2), np.float32))
        y = nd.cast_storage(x, "default")
        assert y is not x
        y[:] = 0.0
        np.testing.assert_allclose(x.asnumpy(), 1.0)
