"""NDArray tests (model: reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-6):
    a = a.asnumpy() if isinstance(a, nd.NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, nd.NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)


def test_creation():
    assert nd.zeros((2, 3)).shape == (2, 3)
    assert nd.ones(4).asnumpy().sum() == 4
    assert nd.full((2, 2), 7).asnumpy()[0, 0] == 7
    assert nd.arange(5).shape == (5,)
    assert nd.arange(0, 4, repeat=2).shape == (8,)
    assert nd.eye(3).asnumpy()[1, 1] == 1
    a = nd.array([[1, 2], [3, 4]])
    assert a.dtype == np.dtype("float32")  # list input defaults to float32
    b = nd.array(np.float64([1.5]))  # float64 downcast to float32 by default
    assert b.dtype == np.dtype("float32")
    c = nd.array(np.array([1, 2], np.int8))
    assert c.dtype == np.dtype("int8")  # numpy input keeps dtype


def test_arith_broadcast():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([10.0, 20.0])
    assert_almost_equal(a + b, np.array([[11, 22], [13, 24]], np.float32))
    assert_almost_equal(a - 1, np.array([[0, 1], [2, 3]], np.float32))
    assert_almost_equal(2 / a, 2 / a.asnumpy())
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(a % 3, a.asnumpy() % 3)
    assert_almost_equal(nd.maximum(a, 2.5), np.maximum(a.asnumpy(), 2.5))
    assert_almost_equal(-a, -a.asnumpy())


def test_comparisons_are_float():
    a = nd.array([1.0, 2.0, 3.0])
    e = a == 2.0
    assert e.dtype == np.dtype("float32")
    assert_almost_equal(e, [0, 1, 0])
    assert_almost_equal(a > 1.5, [0, 1, 1])


def test_inplace_ops():
    a = nd.ones((3,))
    a += 2
    assert_almost_equal(a, [3, 3, 3])
    a *= 2
    assert_almost_equal(a, [6, 6, 6])
    a[1] = 0
    assert_almost_equal(a, [6, 0, 6])
    a[:] = 5
    assert_almost_equal(a, [5, 5, 5])


def test_indexing():
    a = nd.arange(12).reshape(3, 4)
    assert a[1].shape == (4,)
    assert a[1, 2].asscalar() == 6
    assert a[0:2].shape == (2, 4)
    assert a[:, 1::2].shape == (3, 2)
    idx = nd.array([0, 2])
    assert nd.take(a, idx, axis=0).shape == (2, 4)
    got = a[nd.array([0, 2]).astype("int32"), :]
    assert got.shape == (2, 4)


def test_reshape_special_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape(0, -1).shape == (2, 12)
    assert a.reshape(-2,).shape == (2, 3, 4)
    assert a.reshape(-3, 0).shape == (6, 4)
    assert a.reshape(0, -4, 3, 1, 0).shape == (2, 3, 1, 4)
    assert a.reshape(6, -1).shape == (6, 4)


def test_reductions():
    a = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert_almost_equal(a.sum(), a.asnumpy().sum())
    assert_almost_equal(a.sum(axis=1), a.asnumpy().sum(1))
    assert_almost_equal(a.mean(axis=(0, 2)), a.asnumpy().mean((0, 2)))
    assert_almost_equal(a.max(axis=2, keepdims=True),
                        a.asnumpy().max(2, keepdims=True))
    assert a.argmax(axis=1).dtype == np.dtype("float32")
    assert_almost_equal(nd.norm(a), np.sqrt((a.asnumpy() ** 2).sum()))


def test_dot_and_batch_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    assert_almost_equal(nd.dot(a, b), a.asnumpy() @ b.asnumpy())
    assert_almost_equal(nd.dot(a, b.T.copy(), transpose_b=True),
                        a.asnumpy() @ b.asnumpy())
    x = nd.array(np.random.rand(2, 3, 4).astype(np.float32))
    y = nd.array(np.random.rand(2, 4, 5).astype(np.float32))
    assert_almost_equal(nd.batch_dot(x, y),
                        np.matmul(x.asnumpy(), y.asnumpy()))


def test_shape_ops():
    a = nd.arange(6).reshape(2, 3)
    assert nd.transpose(a).shape == (3, 2)
    assert nd.expand_dims(a, 1).shape == (2, 1, 3)
    assert nd.concat(a, a, dim=0).shape == (4, 3)
    assert nd.stack(a, a, axis=0).shape == (2, 2, 3)
    parts = nd.split(a, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1)
    sq = nd.split(a, 3, axis=1, squeeze_axis=True)
    assert sq[0].shape == (2,)
    assert nd.tile(a, (2, 2)).shape == (4, 6)
    assert nd.repeat(a, 2, axis=0).shape == (4, 3)
    assert nd.flip(a, 1).asnumpy()[0, 0] == 2
    assert nd.slice(a, (0, 1), (2, 3)).shape == (2, 2)
    assert nd.slice_axis(a, 1, 0, 2).shape == (2, 2)
    assert nd.pad(a.reshape(1, 1, 2, 3), mode="constant",
                  pad_width=(0, 0, 0, 0, 1, 1, 1, 1)).shape == (1, 1, 4, 5)
    assert nd.broadcast_to(nd.ones((1, 3)), (4, 3)).shape == (4, 3)
    assert nd.broadcast_axis(nd.ones((1, 3)), axis=0, size=5).shape == (5, 3)
    assert nd.where(a > 2, a, nd.zeros_like(a)).asnumpy()[0, 0] == 0


def test_activations():
    x = nd.array([-2.0, 0.0, 2.0])
    assert_almost_equal(nd.relu(x), [0, 0, 2])
    assert_almost_equal(nd.sigmoid(x), 1 / (1 + np.exp([2.0, 0, -2.0])),
                        rtol=1e-4)
    assert_almost_equal(nd.softmax(x).sum(), 1.0)
    assert_almost_equal(nd.log_softmax(x), np.log(nd.softmax(x).asnumpy()),
                        rtol=1e-4)
    assert_almost_equal(nd.leaky_relu(x, slope=0.1), [-0.2, 0, 2])
    assert_almost_equal(nd.Activation(x, "tanh"), np.tanh(x.asnumpy()),
                        rtol=1e-4)


def test_softmax_with_length():
    x = nd.array(np.random.rand(2, 5).astype(np.float32))
    ln = nd.array([3, 5])
    out = nd.softmax(x, axis=-1, length=ln).asnumpy()
    assert out[0, 3:].sum() == 0
    np.testing.assert_allclose(out.sum(-1), [1, 1], rtol=1e-5)


def test_ordering():
    x = nd.array([3.0, 1.0, 2.0])
    assert_almost_equal(nd.sort(x), [1, 2, 3])
    assert_almost_equal(nd.sort(x, is_ascend=False), [3, 2, 1])
    assert_almost_equal(nd.argsort(x), [1, 2, 0])
    assert_almost_equal(nd.topk(x, k=2), [0, 2])       # indices, descending
    assert_almost_equal(nd.topk(x, k=2, ret_typ="value"), [3, 2])
    v, i = nd.topk(x, k=1, ret_typ="both")
    assert v.asscalar() == 3 and i.asscalar() == 0


def test_pick_onehot_gather():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert_almost_equal(nd.pick(x, nd.array([0, 1])), [1, 4])
    oh = nd.one_hot(nd.array([0, 2]), 3)
    assert_almost_equal(oh, [[1, 0, 0], [0, 0, 1]])
    g = nd.gather_nd(x, nd.array([[0, 1], [0, 1]]))
    assert_almost_equal(g, [1, 4])


def test_sequence_ops():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 2, 2))  # (T,B,C)
    sl = nd.array([2, 3])
    m = nd.SequenceMask(x, sl, use_sequence_length=True, value=-1)
    assert m.asnumpy()[2, 0, 0] == -1 and m.asnumpy()[2, 1, 0] == 10
    last = nd.SequenceLast(x, sl, use_sequence_length=True)
    assert last.shape == (2, 2)
    np.testing.assert_allclose(last.asnumpy()[0], x.asnumpy()[1, 0])
    rev = nd.SequenceReverse(x, sl, use_sequence_length=True)
    np.testing.assert_allclose(rev.asnumpy()[0, 0], x.asnumpy()[1, 0])


def test_cast_copy_context():
    a = nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == np.dtype("float16")
    c = a.copyto(mx.cpu(0))
    assert c.context.device_type == "cpu"
    d = a.as_in_context(mx.cpu(0))
    assert d.context == mx.cpu(0)
    a2 = nd.zeros((2, 2))
    a.copyto(a2)
    assert_almost_equal(a2, np.ones((2, 2)))


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert int(a) == 3
    assert bool(a)
    assert a.item() == 3.5
    with pytest.raises(mx.MXNetError):
        nd.ones((2,)).asscalar()


def test_random():
    mx.random.seed(7)
    u1 = nd.random.uniform(shape=(100,))
    mx.random.seed(7)
    u2 = nd.random.uniform(shape=(100,))
    assert_almost_equal(u1, u2)  # deterministic under same seed
    n = nd.random.normal(0, 1, shape=(1000,))
    assert abs(float(n.mean().asscalar())) < 0.2
    r = nd.random.randint(0, 10, shape=(50,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10
    # shape-check the rest of the sampler family
    assert nd.random.poisson(2.0, shape=(5,)).shape == (5,)
    assert nd.random.exponential(1.0, shape=(5,)).shape == (5,)
    assert nd.random.gamma(2.0, 2.0, shape=(5,)).shape == (5,)


def test_add_n_and_misc():
    a, b, c = nd.ones((2,)), nd.ones((2,)) * 2, nd.ones((2,)) * 3
    assert_almost_equal(nd.add_n(a, b, c), [6, 6])
    assert_almost_equal(nd.clip(nd.array([-1.0, 5.0]), 0, 1), [0, 1])
    assert nd.shape_array(a).asnumpy()[0] == 2
    assert nd.stop_gradient(a) is not None
    assert_almost_equal(nd.smooth_l1(nd.array([0.5, 2.0])), [0.125, 1.5])


def test_waitall_and_async_error_surfacing():
    nd.waitall()
    # async error should surface at sync point as MXNetError
    with pytest.raises(Exception):
        bad = nd.dot(nd.ones((2, 3)), nd.ones((2, 3)))  # shape mismatch
        bad.wait_to_read()
