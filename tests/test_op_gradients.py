"""Finite-difference gradient sweep over the op corpus (reference:
python/mxnet/test_utils.py check_numeric_gradient applied the way
tests/python/unittest/test_operator.py does — the universal grad test).

Every differentiable op family gets its Jacobian action checked against
central differences on small shapes.  Non-differentiable ops (comparisons,
argmax, rounding) get a forward-only sanity pass instead.
"""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import test_utils as tu

nd = mx.nd


def _rng(seed=0):
    return onp.random.default_rng(seed)


def _u(lo, hi, shape=(3, 4), seed=0):
    return (_rng(seed).random(shape) * (hi - lo) + lo).astype(onp.float64)


# --------------------------------------------------------------- unary ops
# (name, input-domain) — domains avoid kinks/poles so central differences
# are valid
UNARY = [
    ("abs", (0.2, 2.0)), ("negative", (-2, 2)), ("reciprocal", (0.5, 2.0)),
    ("square", (-2, 2)), ("sqrt", (0.2, 3.0)), ("rsqrt", (0.3, 3.0)),
    ("cbrt", (0.2, 3.0)), ("rcbrt", (0.3, 3.0)), ("exp", (-1, 1)),
    ("expm1", (-1, 1)), ("log", (0.2, 3.0)), ("log10", (0.2, 3.0)),
    ("log2", (0.2, 3.0)), ("log1p", (-0.5, 2.0)), ("sin", (-2, 2)),
    ("cos", (-2, 2)), ("tan", (-1.0, 1.0)), ("arcsin", (-0.8, 0.8)),
    ("arccos", (-0.8, 0.8)), ("arctan", (-2, 2)), ("sinh", (-1.5, 1.5)),
    ("cosh", (-1.5, 1.5)), ("tanh", (-1.5, 1.5)),
    ("arcsinh", (-2, 2)), ("arccosh", (1.3, 3.0)),
    ("arctanh", (-0.7, 0.7)), ("degrees", (-2, 2)), ("radians", (-90, 90)),
    ("gammaln", (0.5, 3.0)), ("digamma", (0.8, 3.0)), ("erf", (-1.5, 1.5)),
    ("erfinv", (-0.7, 0.7)), ("relu", (0.1, 2.0)), ("sigmoid", (-2, 2)),
    ("softsign", (0.2, 2.0)), ("softrelu", (-2, 2)), ("gelu", (-2, 2)),
    ("erf_gelu", (-2, 2)), ("identity", (-2, 2)),
]


@pytest.mark.parametrize("name,domain", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_grad(name, domain):
    fn = getattr(nd, name)
    tu.check_numeric_gradient(lambda x: fn(x), [_u(*domain, seed=1)])


# non-differentiable unaries: forward matches numpy
UNARY_FWD = [
    ("sign", onp.sign, (-2, 2)), ("floor", onp.floor, (-2, 2)),
    ("ceil", onp.ceil, (-2, 2)), ("trunc", onp.trunc, (-2, 2)),
    ("rint", onp.rint, (-2, 2)), ("round", onp.round, (-2, 2)),
    ("fix", onp.trunc, (-2, 2)),
    ("isnan", onp.isnan, (-2, 2)), ("isinf", onp.isinf, (-2, 2)),
    ("isfinite", onp.isfinite, (-2, 2)),
]


@pytest.mark.parametrize("name,ref,domain", UNARY_FWD,
                         ids=[u[0] for u in UNARY_FWD])
def test_unary_forward(name, ref, domain):
    x = _u(*domain, seed=2).astype(onp.float32)
    fn = getattr(nd, name)
    tu.assert_almost_equal(fn(nd.array(x)).asnumpy().astype(onp.float64),
                           ref(x).astype(onp.float64))


# -------------------------------------------------------------- binary ops
BINARY = [
    ("add", (-2, 2), (-2, 2)), ("subtract", (-2, 2), (-2, 2)),
    ("multiply", (-2, 2), (-2, 2)), ("divide", (-2, 2), (0.5, 2.0)),
    ("power", (0.5, 2.0), (0.5, 2.0)), ("maximum", (-2, 2), (-2, 2)),
    ("minimum", (-2, 2), (-2, 2)), ("hypot", (0.5, 2), (0.5, 2)),
    ("arctan2", (0.5, 2), (0.5, 2)), ("mod", (0.6, 3.0), (3.5, 5.0)),
]


@pytest.mark.parametrize("name,da,db", BINARY, ids=[b[0] for b in BINARY])
def test_binary_grad(name, da, db):
    fn = getattr(nd, name)
    tu.check_numeric_gradient(
        lambda a, b: fn(a, b), [_u(*da, seed=3), _u(*db, seed=4)])


def test_binary_broadcast_grad():
    # broadcasting across mismatched shapes (reference:
    # elemwise_binary_broadcast_op)
    tu.check_numeric_gradient(
        lambda a, b: nd.broadcast_add(a, b),
        [_u(-2, 2, (3, 4)), _u(-2, 2, (1, 4))])
    tu.check_numeric_gradient(
        lambda a, b: nd.broadcast_mul(a, b),
        [_u(-2, 2, (3, 1)), _u(-2, 2, (3, 4))])


BINARY_FWD = [("equal", onp.equal), ("not_equal", onp.not_equal),
              ("greater", onp.greater), ("greater_equal", onp.greater_equal),
              ("lesser", onp.less), ("lesser_equal", onp.less_equal)]


@pytest.mark.parametrize("name,ref", BINARY_FWD,
                         ids=[b[0] for b in BINARY_FWD])
def test_binary_compare_forward(name, ref):
    if not hasattr(nd, name):
        pytest.skip(f"no {name}")
    a = onp.array([[1.0, 2.0], [3.0, 4.0]], onp.float32)
    b = onp.array([[2.0, 2.0], [1.0, 4.0]], onp.float32)
    out = getattr(nd, name)(nd.array(a), nd.array(b)).asnumpy()
    tu.assert_almost_equal(out, ref(a, b).astype(onp.float32))


# -------------------------------------------------------------- reductions
REDUCE = [("sum", {}), ("mean", {}), ("prod", {}),
          ("sum", {"axis": 0}), ("mean", {"axis": 1}),
          ("sum", {"axis": 1, "keepdims": True}),
          ("nansum", {}), ("nanprod", {}),
          ("max", {"axis": 1}), ("min", {"axis": 0}),
          ("norm", {}), ("norm", {"ord": 1})]


@pytest.mark.parametrize("name,kw", REDUCE,
                         ids=[f"{r[0]}-{r[1]}" for r in REDUCE])
def test_reduce_grad(name, kw):
    fn = getattr(nd, name)
    dom = (0.5, 2.0) if name in ("prod", "nanprod", "norm") else (-2, 2)
    tu.check_numeric_gradient(lambda x: fn(x, **kw),
                              [_u(*dom, (3, 4), seed=5)])


def test_cumsum_grad():
    tu.check_numeric_gradient(lambda x: nd.cumsum(x, axis=1),
                              [_u(-2, 2, (3, 4))])


# ---------------------------------------------------------- linalg/matmul
def test_dot_grad():
    tu.check_numeric_gradient(lambda a, b: nd.dot(a, b),
                              [_u(-1, 1, (3, 4)), _u(-1, 1, (4, 2))])


def test_batch_dot_grad():
    tu.check_numeric_gradient(
        lambda a, b: nd.batch_dot(a, b),
        [_u(-1, 1, (2, 3, 4)), _u(-1, 1, (2, 4, 2))])


def test_linalg_gemm2_grad():
    tu.check_numeric_gradient(
        lambda a, b: nd.linalg_gemm2(a, b),
        [_u(-1, 1, (3, 4)), _u(-1, 1, (4, 2))])


def test_matmul_grad():
    tu.check_numeric_gradient(lambda a, b: nd.matmul(a, b),
                              [_u(-1, 1, (3, 4)), _u(-1, 1, (4, 2))])


# -------------------------------------------------------- shape/index ops
SHAPE_OPS = [
    ("reshape", lambda x: nd.reshape(x, (4, 3)), (3, 4)),
    ("flatten", lambda x: nd.flatten(x), (2, 3, 2)),
    ("transpose", lambda x: nd.transpose(x), (3, 4)),
    ("swapaxes", lambda x: nd.swapaxes(x, 0, 1), (3, 4)),
    ("expand_dims", lambda x: nd.expand_dims(x, 1), (3, 4)),
    ("squeeze", lambda x: nd.squeeze(x), (3, 1, 4)),
    ("broadcast_to", lambda x: nd.broadcast_to(x, (3, 4)), (1, 4)),
    ("tile", lambda x: nd.tile(x, (2, 2)), (2, 3)),
    ("repeat", lambda x: nd.repeat(x, 2, axis=0), (2, 3)),
    ("flip", lambda x: nd.flip(x, axis=1), (3, 4)),
    ("pad2", lambda x: nd.slice(x, (0, 0), (2, 3)), (3, 4)),
    ("slice_axis", lambda x: nd.slice_axis(x, 1, 1, 3), (3, 4)),
    ("diag", lambda x: nd.diag(x), (4, 4)),
    ("clip", lambda x: nd.clip(x, -0.8, 0.8), (3, 4)),
]


@pytest.mark.parametrize("name,fn,shape", SHAPE_OPS,
                         ids=[s[0] for s in SHAPE_OPS])
def test_shape_op_grad(name, fn, shape):
    dom = (-2, 2) if name != "clip" else (-0.5, 0.5)
    tu.check_numeric_gradient(fn, [_u(*dom, shape, seed=6)])


def test_concat_stack_split_grad():
    tu.check_numeric_gradient(
        lambda a, b: nd.concat(a, b, dim=1),
        [_u(-1, 1, (2, 3)), _u(-1, 1, (2, 2))])
    tu.check_numeric_gradient(
        lambda a, b: nd.stack(a, b, axis=0),
        [_u(-1, 1, (2, 3)), _u(-1, 1, (2, 3))])
    tu.check_numeric_gradient(
        lambda x: nd.split(x, num_outputs=2, axis=1)[0],
        [_u(-1, 1, (2, 4))])


def test_take_pick_gather_grad():
    idx = onp.array([0, 2], onp.int32)
    tu.check_numeric_gradient(
        lambda x: nd.take(x, nd.array(idx, dtype=onp.int32)),
        [_u(-1, 1, (4, 3))])
    pick_idx = onp.array([0, 1, 2], onp.float32)
    tu.check_numeric_gradient(
        lambda x: nd.pick(x, nd.array(pick_idx), axis=1),
        [_u(-1, 1, (3, 4))])
    gnd_idx = onp.array([[0, 2]], onp.int32)
    tu.check_numeric_gradient(
        lambda x: nd.gather_nd(x, nd.array(gnd_idx, dtype=onp.int32)),
        [_u(-1, 1, (4, 3))])


def test_where_embedding_grad():
    cond = onp.array([[1, 0, 1, 0]] * 3, onp.float32)
    tu.check_numeric_gradient(
        lambda a, b: nd.where(nd.array(cond), a, b),
        [_u(-1, 1, (3, 4)), _u(-1, 1, (3, 4))])
    eidx = onp.array([[0, 2], [1, 1]], onp.float32)
    tu.check_numeric_gradient(
        lambda w: nd.Embedding(nd.array(eidx), w, input_dim=4,
                               output_dim=3),
        [_u(-1, 1, (4, 3))])


def test_sequence_ops_grad():
    x = _u(-1, 1, (4, 2, 3))                       # (seq, batch, feat)
    length = onp.array([2, 4], onp.float32)
    tu.check_numeric_gradient(
        lambda d: nd.SequenceMask(d, nd.array(length),
                                  use_sequence_length=True), [x])
    tu.check_numeric_gradient(
        lambda d: nd.SequenceLast(d, nd.array(length),
                                  use_sequence_length=True), [x])
    tu.check_numeric_gradient(
        lambda d: nd.SequenceReverse(d, nd.array(length),
                                     use_sequence_length=True), [x])


def test_misc_grad():
    tu.check_numeric_gradient(
        lambda a, b, c: nd.add_n(a, b, c),
        [_u(-1, 1, (2, 3), seed=i) for i in range(3)])
    tu.check_numeric_gradient(lambda x: nd.smooth_l1(x, scalar=1.0),
                              [_u(0.3, 2.0, (3, 4))])
    tu.check_numeric_gradient(lambda x: nd.l2_normalization(x),
                              [_u(0.5, 2.0, (3, 4))])
    tu.check_numeric_gradient(lambda x: nd.batch_take(
        x, nd.array(onp.array([0, 2, 1], onp.int32), dtype=onp.int32)),
        [_u(-1, 1, (3, 4))])


# ----------------------------------------------------------------- nn ops
def test_softmax_family_grad():
    tu.check_numeric_gradient(lambda x: nd.softmax(x), [_u(-2, 2, (3, 4))])
    tu.check_numeric_gradient(lambda x: nd.log_softmax(x),
                              [_u(-2, 2, (3, 4))])
    tu.check_numeric_gradient(lambda x: nd.softmax(x, axis=0),
                              [_u(-2, 2, (3, 4))])


def test_activation_grad():
    for act in ("relu", "sigmoid", "tanh", "softrelu", "softsign"):
        dom = (0.1, 2.0) if act in ("relu",) else (-2, 2)
        tu.check_numeric_gradient(
            lambda x, a=act: nd.Activation(x, act_type=a),
            [_u(*dom, (3, 4), seed=7)])
    tu.check_numeric_gradient(lambda x: nd.leaky_relu(x, slope=0.1),
                              [_u(0.1, 2.0, (3, 4))])


def test_fully_connected_grad():
    tu.check_numeric_gradient(
        lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=3),
        [_u(-1, 1, (2, 4)), _u(-1, 1, (3, 4)), _u(-1, 1, (3,))])


def test_convolution_grad():
    tu.check_numeric_gradient(
        lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3),
                                       num_filter=2, pad=(1, 1)),
        [_u(-1, 1, (1, 2, 5, 5)), _u(-1, 1, (2, 2, 3, 3)),
         _u(-1, 1, (2,))], rtol=2e-2)


def test_deconvolution_grad():
    tu.check_numeric_gradient(
        lambda x, w: nd.Deconvolution(x, w, kernel=(2, 2), num_filter=2,
                                      no_bias=True),
        [_u(-1, 1, (1, 2, 4, 4)), _u(-1, 1, (2, 2, 2, 2))], rtol=2e-2)


def test_pooling_grad():
    tu.check_numeric_gradient(
        lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="avg",
                             stride=(2, 2)),
        [_u(-1, 1, (1, 2, 4, 4))])
    # max pool: keep values distinct so the argmax is stable under eps
    base = onp.arange(32, dtype=onp.float64).reshape(1, 2, 4, 4) * 0.37
    tu.check_numeric_gradient(
        lambda x: nd.Pooling(x, kernel=(2, 2), pool_type="max",
                             stride=(2, 2)), [base])


def test_norm_layers_grad():
    x = _u(-1, 1, (2, 3, 4))
    g, b = _u(0.5, 1.5, (3,)), _u(-0.5, 0.5, (3,))
    tu.check_numeric_gradient(
        lambda d, gg, bb: nd.LayerNorm(d, gg, bb, axis=-1),
        [_u(-1, 1, (3, 4)), _u(0.5, 1.5, (4,)), _u(-0.5, 0.5, (4,))])
    tu.check_numeric_gradient(
        lambda d, gg, bb: nd.InstanceNorm(d, gg, bb),
        [x, g, b], rtol=2e-2)
    tu.check_numeric_gradient(
        lambda d, gg, bb: nd.GroupNorm(d, gg, bb, num_groups=1),
        [_u(-1, 1, (2, 2, 4)), _u(0.5, 1.5, (1,)), _u(-0.5, 0.5, (1,))],
        rtol=2e-2)


def test_batchnorm_grad():
    x = _u(-1, 1, (2, 3, 4))
    tu.check_numeric_gradient(
        lambda d, gg, bb: nd.BatchNorm(
            d, gg, bb, nd.zeros((3,)), nd.ones((3,)), fix_gamma=False),
        [x, _u(0.5, 1.5, (3,)), _u(-0.5, 0.5, (3,))], rtol=2e-2)


def test_softmax_cross_entropy_grad():
    lab = onp.array([0, 2], onp.float32)
    tu.check_numeric_gradient(
        lambda x: nd.softmax_cross_entropy(x, nd.array(lab)),
        [_u(-1, 1, (2, 4))])


def test_upsampling_grad():
    tu.check_numeric_gradient(
        lambda x: nd.UpSampling(x, scale=2, sample_type="nearest"),
        [_u(-1, 1, (1, 2, 3, 3))])


# --------------------------------------------------- contrib/detection ops
@pytest.mark.slow
def test_contrib_grads():
    from incubator_mxnet_tpu.ndarray import contrib as C
    tu.check_numeric_gradient(
        lambda x: C.AdaptiveAvgPooling2D(x, output_size=2),
        [_u(-1, 1, (1, 2, 4, 4))])
    tu.check_numeric_gradient(
        lambda x: C.BilinearResize2D(x, height=6, width=6),
        [_u(-1, 1, (1, 2, 3, 3))], rtol=2e-2)
    rois = onp.array([[0, 0, 0, 3, 3]], onp.float32)
    tu.check_numeric_gradient(
        lambda x: C.ROIAlign(x, nd.array(rois), pooled_size=(2, 2),
                             spatial_scale=1.0),
        [_u(0.2, 1.0, (1, 1, 5, 5))], rtol=2e-2)


# ------------------------------------------------------- consistency tier
def test_check_consistency_smoke():
    tu.check_consistency(lambda a, b: nd.dot(a, b),
                         [_u(-1, 1, (3, 4)), _u(-1, 1, (4, 2))],
                         ctx_list=[mx.cpu(0), mx.cpu(0)])


def test_stop_gradient_blocks_grad():
    # FD can't check this (perturbation leaks through the stopped branch);
    # analytic contract: d/dx sum(x * sg(x)) == sg(x), not 2x
    x = _u(-1, 1, (3, 4))
    tu.check_symbolic_backward(
        lambda a: a * nd.stop_gradient(a), [x],
        [onp.ones((3, 4))], [x])


def test_check_symbolic_forward_backward():
    x = onp.array([[1.0, 2.0], [3.0, 4.0]])
    tu.check_symbolic_forward(lambda a: a * 2, [x], [x * 2])
    tu.check_symbolic_backward(lambda a: a * a, [x],
                               [onp.ones_like(x)], [2 * x])
