"""ZeRO-1 weight-update sharding (arXiv:2004.13336) tests.

Covers the PR's contract: ShardSpec layout bookkeeping (uneven padding
round-trip, dtype grouping, per-leaf scalar expansion), bit parity of
the zero1 fused step vs the replicated fused step for every elementwise
rule on the 8-virtual-device dp mesh, the ONE-donated-dispatch
invariant (jit-cache counters at the ``zero1_update`` site), the
memory / traffic gauges (state bytes >= 4x reduction, all-gather
volume), LAMB fallback to the replicated path, flush/rehydrate of the
flat shards around out-of-envelope steps, SPMDTrainer + CompiledLoop
wiring (dp-sharded state leaves, k-step chunk parity), shard-count-
agnostic checkpoints (save at N=8, resume at N=4, interop with
non-zero1 trainers), and the reduce-scatter-shaped kvstore pushpull.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd as ag
from incubator_mxnet_tpu import fault, parallel, telemetry
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.checkpoint import AsyncCheckpointer
from incubator_mxnet_tpu.gluon import Trainer, loss as gloss, nn
from incubator_mxnet_tpu.parallel import zero1 as z1
from incubator_mxnet_tpu.parallel.loop import CompiledLoop


@pytest.fixture(autouse=True)
def _clean_state():
    fault.clear_plan()
    telemetry.stop()
    telemetry.reset()
    yield
    fault.clear_plan()
    telemetry.stop()
    telemetry.reset()


def _devices():
    import jax
    return jax.devices()


# ------------------------------------------------- ShardSpec bookkeeping
def test_shard_spec_uneven_padding_roundtrip():
    """Leaf sizes that do not divide the shard count are zero-padded to
    the next multiple; flatten/unflatten is the exact inverse."""
    rng = np.random.default_rng(0)
    leaves = [rng.standard_normal(s).astype(np.float32)
              for s in [(5,), (3, 4), (2, 1, 3)]]          # total 23
    spec = z1.build_shard_spec(leaves, 8)
    assert spec.n_shards == 8 and spec.n_leaves == 3
    (seg,) = spec.segments
    assert seg.total == 23 and seg.padded == 24
    assert seg.padded % 8 == 0
    flat = np.asarray(z1.flatten_segment(seg, leaves))
    assert flat.shape == (24,)
    np.testing.assert_array_equal(flat[23:], 0.0)          # the padding
    back = z1.unflatten_tree(spec, (flat,))
    for a, b in zip(leaves, back):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_shard_spec_groups_by_dtype_and_empty_pad():
    """Mixed dtypes split into per-dtype segments (order preserved);
    an exactly-divisible segment gets no padding."""
    leaves = [np.zeros((4,), np.float32), np.zeros((2, 3), np.float16),
              np.zeros((4,), np.float32), np.zeros((2,), np.float16)]
    spec = z1.build_shard_spec(leaves, 8)
    assert len(spec.segments) == 2
    f32, f16 = spec.segments
    assert f32.idx == (0, 2) and f32.total == 8 and f32.padded == 8
    assert f16.idx == (1, 3) and f16.total == 8 and f16.padded == 8
    with pytest.raises(MXNetError):
        z1.build_shard_spec(leaves, 0)


def test_expand_per_leaf_matches_broadcast():
    """Per-leaf scalars expanded over the flat layout multiply exactly
    like broadcasting each scalar over its own leaf."""
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    leaves = [rng.standard_normal(s).astype(np.float32)
              for s in [(3,), (2, 2)]]                     # total 7
    scalars = [jnp.float32(0.5), jnp.float32(-2.0)]
    spec = z1.build_shard_spec(leaves, 4)
    (seg,) = spec.segments
    flat = z1.flatten_segment(seg, leaves)
    vec = z1.expand_per_leaf(seg, scalars)
    prod = np.asarray(flat * vec)
    back = z1.unflatten_tree(spec, (prod,))
    for leaf, s, got in zip(leaves, scalars, back):
        np.testing.assert_array_equal(leaf * np.float32(s),
                                      np.asarray(got))


def test_state_and_allgather_byte_accounting():
    leaves = [np.zeros((10,), np.float32), np.zeros((3,), np.float32)]
    assert z1.per_replica_state_bytes({"m": tuple(leaves)}) == 13 * 4
    spec = z1.build_shard_spec(leaves, 8)                  # padded 16
    assert z1.zero1_allgather_bytes(spec) == 16 * 4 * 7 // 8


# --------------------------------------------- Trainer zero1 bit parity
def _make_net(dtype="float32"):
    np.random.seed(7)
    mx.random.seed(7)
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = mx.nd.array(np.random.randn(5, 6).astype(dtype))
    y = mx.nd.array(np.random.randn(5, 3).astype(dtype))
    if dtype != "float32":
        net.cast(dtype)
    net(x)
    return net, x, y


def _train(optimizer, opt_params, zero1, steps=4, dtype="float32"):
    net, x, y = _make_net(dtype)
    trainer = Trainer(net.collect_params(), optimizer, dict(opt_params),
                      fused=True, zero1=zero1)
    loss_fn = gloss.L2Loss()
    for _ in range(steps):
        with ag.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(5)
    params = [p.data().asnumpy()
              for p in net.collect_params().values()]
    return params, trainer


def _states(trainer):
    if trainer._fused is not None:
        trainer._fused.flush_states()
    out = []
    for i in sorted(trainer._updaters.states):
        out.append(_flatten_state(trainer._updaters.states[i]))
    return out


def _flatten_state(s):
    if s is None:
        return []
    if isinstance(s, tuple):
        return [a for x in s for a in _flatten_state(x)]
    return [s.asnumpy()]


ZERO1_CONFIGS = [
    ("sgd", {"learning_rate": 0.05}),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-3}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-3}),
    ("adamw", {"learning_rate": 0.01, "wd": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "wd": 1e-4}),
    ("adagrad", {"learning_rate": 0.05, "wd": 1e-3}),
    ("adam", {"learning_rate": 0.01, "clip_gradient": 0.1}),
]


@pytest.mark.parametrize("optimizer,opt_params", ZERO1_CONFIGS)
def test_zero1_matches_replicated_fused_bitwise(optimizer, opt_params):
    """The acceptance bar: the sharded update on the 8-device dp mesh is
    BIT-identical to the replicated fused step — params AND optimizer
    state (flushed back from the flat shards)."""
    z_p, z_tr = _train(optimizer, opt_params, zero1=True)
    r_p, r_tr = _train(optimizer, opt_params, zero1=False)
    assert z_tr._fused._z_mesh is not None
    assert z_tr._fused._z_state is not None        # shards engaged
    for a, b in zip(z_p, r_p):
        assert np.array_equal(a, b)
    for sa, sb in zip(_states(z_tr), _states(r_tr)):
        assert len(sa) == len(sb)
        for a, b in zip(sa, sb):
            assert np.array_equal(a, b)


def test_zero1_fp16_multi_precision_bitwise():
    cfg = {"learning_rate": 0.1, "momentum": 0.9,
           "multi_precision": True, "clip_gradient": 0.5}
    z_p, z_tr = _train("sgd", cfg, zero1=True, dtype="float16")
    r_p, r_tr = _train("sgd", cfg, zero1=False, dtype="float16")
    assert z_tr._fused._z_state is not None
    for a, b in zip(z_p, r_p):
        assert a.dtype == np.float16 and np.array_equal(a, b)
    for sa, sb in zip(_states(z_tr), _states(r_tr)):
        for a, b in zip(sa, sb):
            assert a.dtype == np.float32 and np.array_equal(a, b)


# ------------------------------------- dispatch count + memory telemetry
def test_zero1_single_dispatch_and_gauges():
    """One donated dispatch per step (jit-cache counters at the
    zero1_update site see every call), state-bytes gauge >= 4x below
    the replicated gauge, all-gather gauge set to the spec's volume."""
    steps = 4
    telemetry.start()
    _train("adam", {"learning_rate": 0.01, "wd": 1e-3}, zero1=False,
           steps=steps)
    full_bytes = telemetry.counters_flat()["mxtpu_optimizer_state_bytes"]
    telemetry.stop()
    telemetry.reset()

    telemetry.start()
    _, z_tr = _train("adam", {"learning_rate": 0.01, "wd": 1e-3},
                     zero1=True, steps=steps)
    flat = telemetry.counters_flat()
    assert flat["mxtpu_optimizer_fused_updates"] == steps
    assert flat["mxtpu_optimizer_dispatches_per_step"] == 1
    hits = telemetry.registry.get("mx_compile_cache_hits_total")
    misses = telemetry.registry.get("mx_compile_cache_misses_total")
    site = (("site", "zero1_update"),)
    n_miss = misses._values.get(site, 0)
    n_hit = hits._values.get(site, 0)
    assert 1 <= n_miss <= 2
    assert n_hit + n_miss == steps
    shard_bytes = flat["mxtpu_optimizer_state_bytes"]
    assert full_bytes / shard_bytes >= 4          # the memory win
    assert shard_bytes * 8 >= full_bytes          # only padding above 1/8
    spec = z_tr._fused._z_spec
    assert flat["mxtpu_zero1_allgather_bytes"] == \
        z1.zero1_allgather_bytes(spec) > 0


def test_zero1_lamb_falls_back_to_replicated_fused():
    """LAMB's trust ratio straddles shard boundaries: a zero1 request
    stays on the replicated fused path (still one dispatch, still
    parity) — counted at the fused_update site, not zero1_update."""
    telemetry.start()
    z_p, z_tr = _train("lamb", {"learning_rate": 0.01, "wd": 0.01},
                       zero1=True)
    flat = telemetry.counters_flat()
    assert z_tr._fused._z_state is None
    assert flat["mxtpu_optimizer_fused_updates"] == 4
    misses = telemetry.registry.get("mx_compile_cache_misses_total")
    assert misses._values.get((("site", "zero1_update"),), 0) == 0
    assert misses._values.get((("site", "fused_update"),), 0) >= 1
    r_p, _ = _train("lamb", {"learning_rate": 0.01, "wd": 0.01},
                    zero1=False)
    for a, b in zip(z_p, r_p):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_zero1_flush_and_rehydrate_preserves_momentum():
    """flush_states materializes the 1/N shards into the per-param dict
    (checkpoint format unchanged); further steps re-flatten from it and
    stay bit-identical to an uninterrupted replicated run."""
    net, x, y = _make_net()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.05, "momentum": 0.9},
                      fused=True, zero1=True)
    loss_fn = gloss.L2Loss()

    def _step():
        with ag.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(5)

    _step(); _step()
    assert trainer._fused._z_state is not None
    trainer._fused.flush_states()
    assert trainer._fused._z_state is None
    mom = [a for i in sorted(trainer._updaters.states)
           for a in _flatten_state(trainer._updaters.states[i])]
    assert mom and all(np.isfinite(m).all() for m in mom)
    _step(); _step()                                # re-engages shards
    assert trainer._fused._z_state is not None
    z_p = [p.data().asnumpy() for p in net.collect_params().values()]
    r_p, _ = _train("sgd", {"learning_rate": 0.05, "momentum": 0.9},
                    zero1=False)
    for a, b in zip(z_p, r_p):
        assert np.array_equal(a, b)


def test_zero1_env_var_engages(monkeypatch):
    monkeypatch.setenv("MXNET_ZERO1", "1")
    net, x, y = _make_net()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    assert trainer._zero1_requested
    with ag.record():
        loss = gloss.L2Loss()(net(x), y)
    loss.backward()
    trainer.step(5)                      # _init_kvstore builds _fused
    assert trainer._fused is not None
    assert trainer._fused._z_mesh is not None
    assert trainer._fused._z_state is not None


# --------------------------------------------------- SPMDTrainer wiring
def _spmd_batches():
    rng = np.random.default_rng(3)
    return (rng.standard_normal((16, 8)).astype(np.float32),
            rng.standard_normal((16, 4)).astype(np.float32))


def _spmd_net(prefix):
    mx.random.seed(11)
    net = nn.HybridSequential(prefix=prefix)
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8, activation="relu"))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(init=mx.init.Xavier())
    return net


def test_spmd_zero1_parity_and_sharded_state():
    from jax.sharding import PartitionSpec
    mesh = parallel.make_mesh({"data": 8})
    X, Y = _spmd_batches()
    vals = {}
    for z in (False, True):
        tr = parallel.SPMDTrainer(_spmd_net(f"sz{int(z)}_"),
                                  gloss.L2Loss(), "adamw",
                                  {"learning_rate": 0.01, "wd": 0.01},
                                  mesh=mesh, zero1=z)
        for _ in range(4):
            tr.step(X, Y)
        vals[z] = [np.asarray(v) for v in tr._tr_vals]
        if z:
            import jax
            leaves = jax.tree.leaves(tr._opt_state)
            assert leaves
            for leaf in leaves:
                assert leaf.sharding.spec == PartitionSpec("data")
                assert leaf.ndim == 1          # flat segment buffers
    for a, b in zip(vals[True], vals[False]):
        assert np.array_equal(a, b)


@pytest.mark.parametrize("zero1", [False, True], ids=["replicated", "zero1"])
def test_spmd_bn_momentum_state_sharding_stable(zero1):
    # Regression: with the optimizer-state out_shardings left
    # unconstrained, GSPMD shards data-axis-divisible momentum leaves
    # (BN-channel-sized, 16 % 8 == 0) while the donated input stays
    # replicated — XLA then rejects the executable with an
    # aliased-buffer size mismatch.  The state must leave the step with
    # the shardings it entered with, on both the replicated and the
    # zero1 path.
    import jax
    mx.random.seed(0)
    net = nn.HybridSequential(prefix=f"bnreg{int(zero1)}_")
    with net.name_scope():
        net.add(nn.Conv2D(16, kernel_size=3, padding=1, in_channels=3))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.GlobalAvgPool2D())
        net.add(nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    with mx.autograd.pause():
        net(mx.nd.array(np.zeros((2, 3, 8, 8), np.float32)))
    mesh = parallel.make_mesh({"data": 8})
    tr = parallel.SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
                              {"learning_rate": 0.1, "momentum": 0.9},
                              mesh=mesh, zero1=zero1)
    rng = np.random.RandomState(3)
    x = mx.nd.array(rng.randn(16, 3, 8, 8).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, size=(16,)).astype(np.float32))
    sh0 = [v.sharding for v in jax.tree.leaves(tr._opt_state)]
    for _ in range(2):
        loss = tr.step(x, y)
    assert np.isfinite(float(loss))
    sh1 = [v.sharding for v in jax.tree.leaves(tr._opt_state)]
    assert sh0 == sh1


def test_spmd_zero1_conflicts_raise():
    mesh = parallel.make_mesh({"data": 8})
    net = _spmd_net("cf_")
    with pytest.raises(MXNetError, match="two spellings"):
        parallel.SPMDTrainer(net, gloss.L2Loss(), "sgd",
                             {"learning_rate": 0.1}, mesh=mesh,
                             zero1=True, shard_optimizer_state=True)
    with pytest.raises(MXNetError, match="not elementwise"):
        parallel.SPMDTrainer(net, gloss.L2Loss(), "lamb",
                             {"learning_rate": 0.01}, mesh=mesh,
                             zero1=True)
    with pytest.raises(MXNetError, match="does not compose"):
        parallel.SPMDTrainer(net, gloss.L2Loss(), "sgd",
                             {"learning_rate": 0.1},
                             pipeline_axis="pipe", zero1=True)


def test_spmd_zero1_env_fallback_warns_for_lamb(monkeypatch):
    """MXNET_ZERO1=1 with a non-elementwise rule degrades gracefully:
    warn once, train unsharded."""
    monkeypatch.setenv("MXNET_ZERO1", "1")
    mesh = parallel.make_mesh({"data": 8})
    with pytest.warns(UserWarning, match="MXNET_ZERO1"):
        tr = parallel.SPMDTrainer(_spmd_net("ev_"), gloss.L2Loss(),
                                  "lamb", {"learning_rate": 0.01},
                                  mesh=mesh)
    assert not tr._zero1
    X, Y = _spmd_batches()
    tr.step(X, Y)                                  # still trains


# ------------------------------------------------- CompiledLoop + ckpt
def _loop_batches(n, b=8):
    rng = np.random.default_rng(0)
    return [(rng.standard_normal((b, 8)).astype(np.float32),
             rng.standard_normal((b, 4)).astype(np.float32))
            for _ in range(n)]


def _loop_params(loop):
    return {n.split("_", 1)[1]: np.asarray(v)
            for n, v in loop.params.items()}


def test_loop_zero1_chunk_parity():
    """k=4 chunked scan with the zero1 update inside is bit-identical to
    the non-zero1 loop on the same dp mesh."""
    mesh = parallel.make_mesh({"data": 8})
    batches = _loop_batches(8)
    opt = {"learning_rate": 0.01, "wd": 0.01}
    got = {}
    for z in (False, True):
        net = _spmd_net(f"lp{int(z)}_")
        mx.random.seed(7)
        loop = CompiledLoop(net, gloss.L2Loss(), "adamw", opt,
                            loop_steps=4, mesh=mesh, zero1=z)
        losses = loop.run(batches, prefetch=False)
        assert np.isfinite(losses).all()
        got[z] = (_loop_params(loop), losses)
    for name in got[False][0]:
        assert np.array_equal(got[True][0][name], got[False][0][name])
    assert np.array_equal(got[True][1], got[False][1])


def _ckpt_run(tmp_path, tag, z_save, z_resume):
    """Train 4 batches on the N=8 mesh, checkpoint, resume the SAME
    logical run on the N=4 mesh for 4 more; return final params."""
    batches = _loop_batches(8)
    opt = {"learning_rate": 0.05, "momentum": 0.9}
    mesh8 = parallel.make_mesh({"data": 8})
    net_a = _spmd_net(f"{tag}_")
    mx.random.seed(5)
    loop_a = CompiledLoop(net_a, gloss.L2Loss(), "sgd", opt,
                          loop_steps=2, mesh=mesh8, zero1=z_save)
    loop_a.run(batches[:4], prefetch=False)
    ck = AsyncCheckpointer(str(tmp_path / tag))
    ck.save_sync(4, dict(loop_a.params), trainer=loop_a, epoch=0)

    mesh4 = parallel.make_mesh({"data": 4})
    net_b = _spmd_net(f"{tag}_")                   # same prefix/names
    loop_b = CompiledLoop(net_b, gloss.L2Loss(), "sgd", opt,
                          loop_steps=2, mesh=mesh4, zero1=z_resume)
    ck2 = AsyncCheckpointer(str(tmp_path / tag))
    assert ck2.restore_into(params=net_b.collect_params(),
                            trainer=loop_b) == 4
    loop_b.reload_params()
    loop_b.run(batches[4:], prefetch=False)
    return _loop_params(loop_b)


def test_zero1_checkpoint_shard_count_agnostic(tmp_path):
    """The blob stores the portable per-leaf layout: save at N=8 and
    resume at N=4 (and interop with non-zero1 loops in BOTH
    directions) all land on the same params as the never-sharded run."""
    ref = _ckpt_run(tmp_path, "ref", z_save=False, z_resume=False)
    for tag, zs, zr in [("zz", True, True), ("zn", True, False),
                        ("nz", False, True)]:
        got = _ckpt_run(tmp_path, tag, z_save=zs, z_resume=zr)
        for name in ref:
            assert np.array_equal(ref[name], got[name]), (tag, name)


# --------------------------------------------- kvstore reduce-scatter
def test_pushpull_rs_matches_pushpull():
    """Single process: the RS+AG decomposition is the identity sum —
    bit-equal to pushpull, same out-filling contract, uneven shapes
    round-trip through the padded shard layout."""
    rng = np.random.default_rng(9)
    v = rng.standard_normal((3, 5)).astype(np.float32)     # total 15
    kv = mx.kv.create("dist_sync")
    kv.init("a", mx.nd.zeros((3, 5)))
    kv.init("b", mx.nd.zeros((3, 5)))
    out_rs = mx.nd.zeros((3, 5))
    out_pp = mx.nd.zeros((3, 5))
    kv.pushpull_rs("a", mx.nd.array(v), out=out_rs)
    kv.pushpull("b", mx.nd.array(v), out=out_pp)
    np.testing.assert_array_equal(out_rs.asnumpy(), out_pp.asnumpy())
    pulled = mx.nd.zeros((3, 5))
    kv.pull("a", out=pulled)
    np.testing.assert_array_equal(pulled.asnumpy(), v)


def test_pushpull_rs_fault_sites_preserved():
    """The decomposed path keeps the kvstore.push / kvstore.pull fault
    sites: an injected transient at the reduce-scatter is absorbed by
    the same retry envelope."""
    telemetry.start()
    fault.install_plan("kvstore.push:ioerror@1")
    kv = mx.kv.create("dist_sync")
    kv.init(0, mx.nd.zeros((2, 2)))
    out = mx.nd.zeros((2, 2))
    kv.pushpull_rs(0, mx.nd.ones((2, 2)) * 3, out=out)
    np.testing.assert_array_equal(out.asnumpy(), np.full((2, 2), 3.0))
    assert telemetry.counters_flat()["mxtpu_retries"] >= 1


def test_pushpull_rs_rejects_sparse():
    kv = mx.kv.create("dist_sync")
    kv.init("s", mx.nd.zeros((4, 3)))
    rsp = mx.nd.array(np.eye(4, 3, dtype=np.float32)) \
        .tostype("row_sparse")
    with pytest.raises(MXNetError):
        kv.pushpull_rs("s", rsp)
