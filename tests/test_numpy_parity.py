"""mx.np fine-grained kwarg parity vs real NumPy (VERDICT r03 missing #4):
``out=`` (in-place write + same-object return + dtype cast), ufunc
``where=`` masks, reduction ``where=`` passthrough, and ``order=`` on
reshape/ravel.  Every case runs the same expression through numpy and
through mx.np and compares (reference surface:
python/mxnet/numpy/multiarray.py)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import numpy as np


A = onp.array([[4.0, 9.0], [16.0, 25.0]], onp.float32)
B = onp.array([[1.0, 2.0], [3.0, 4.0]], onp.float32)
M = onp.array([[True, False], [True, True]])


class TestOutKwarg:
    def test_binary_out_same_object(self):
        want = onp.add(A, B)
        out = np.zeros(A.shape)
        got = np.add(np.array(A), np.array(B), out=out)
        assert got is out
        onp.testing.assert_allclose(out.asnumpy(), want)

    def test_unary_out(self):
        out = np.zeros(A.shape)
        got = np.sqrt(np.array(A), out=out)
        assert got is out
        onp.testing.assert_allclose(out.asnumpy(), onp.sqrt(A))

    def test_out_dtype_cast(self):
        """numpy casts the result into out's dtype."""
        out_np = onp.zeros(A.shape, onp.int32)
        onp.add(A, B, out=out_np, casting="unsafe")
        out = np.zeros(A.shape, dtype="int32")
        np.add(np.array(A), np.array(B), out=out)
        assert out.dtype == onp.int32
        onp.testing.assert_array_equal(out.asnumpy(), out_np)

    def test_out_tuple_spelling(self):
        out = np.zeros(A.shape)
        got = np.multiply(np.array(A), np.array(B), out=(out,))
        assert got is out
        onp.testing.assert_allclose(out.asnumpy(), A * B)

    def test_reduction_out(self):
        want = onp.sum(A, axis=0)
        out = np.zeros((2,))
        got = np.sum(np.array(A), axis=0, out=out)
        assert got is out
        onp.testing.assert_allclose(out.asnumpy(), want)

    def test_out_shape_mismatch_raises(self):
        with pytest.raises(mx.MXNetError, match="broadcastable"):
            np.add(np.array(A), np.array(B), out=np.zeros((3, 3)))

    def test_out_wrong_type_raises(self):
        with pytest.raises(mx.MXNetError, match="ndarray"):
            np.add(np.array(A), np.array(B), out=onp.zeros((2, 2)))


class TestWhereKwarg:
    def test_ufunc_where_with_out(self):
        """numpy: masked-out positions keep out's prior value."""
        out_np = onp.full(A.shape, -1.0, onp.float32)
        onp.add(A, B, out=out_np, where=M)
        out = np.full(A.shape, -1.0)
        got = np.add(np.array(A), np.array(B), out=out, where=np.array(M))
        assert got is out
        onp.testing.assert_allclose(out.asnumpy(), out_np)

    def test_ufunc_where_without_out_is_zero_filled(self):
        """numpy leaves False positions uninitialized; this build defines
        them as 0 (the deterministic instance of 'any value')."""
        got = np.sqrt(np.array(A), where=np.array(M)).asnumpy()
        onp.testing.assert_allclose(got[M], onp.sqrt(A)[M])
        onp.testing.assert_allclose(got[~M], 0.0)

    def test_nan_reductions_where_passthrough(self):
        """nanmax/nanmin take reduction-style where= (r04 review: these
        were mis-routed to the ufunc-mask emulation and returned a
        wrong-shaped array)."""
        got = np.nanmax(np.array(A), where=np.array(M), initial=0.0)
        want = onp.nanmax(A, where=M, initial=0.0)
        assert got.shape == ()
        onp.testing.assert_allclose(onp.asarray(got.asnumpy()), want)

    def test_where_mask_blocks_nan_gradients(self):
        """where= must guard the INPUT (double-where), not just the
        output: sqrt of a masked-out negative may not poison grads."""
        from incubator_mxnet_tpu import autograd as ag
        x = np.array(onp.array([4.0, -1.0], onp.float32))
        x.attach_grad()
        with ag.record():
            y = np.sqrt(x, where=x >= 0)
            s = y.sum()
        s.backward()
        g = x.grad.asnumpy()
        onp.testing.assert_allclose(g, [0.25, 0.0], rtol=1e-6)

    def test_reduction_where_passthrough(self):
        for name, kw in [("sum", {}), ("prod", {}), ("mean", {}),
                         ("max", {"initial": -onp.inf}),
                         ("any", {}), ("all", {})]:
            want = getattr(onp, name)(A, where=M, **kw)
            got = getattr(np, name)(np.array(A), where=np.array(M),
                                    **kw)
            onp.testing.assert_allclose(onp.asarray(got.asnumpy()), want,
                                        rtol=1e-6, err_msg=name)


class TestOrderKwarg:
    X = onp.arange(12, dtype=onp.float32).reshape(3, 4)

    @pytest.mark.parametrize("order", ["C", "F", "A"])
    def test_reshape_order(self, order):
        want = onp.reshape(self.X, (4, 3), order=order)
        got = np.reshape(np.array(self.X), (4, 3), order=order)
        onp.testing.assert_array_equal(got.asnumpy(), want)

    @pytest.mark.parametrize("order", ["C", "F", "K", "A"])
    def test_ravel_order(self, order):
        want = onp.ravel(self.X, order=order)
        got = np.ravel(np.array(self.X), order=order)
        onp.testing.assert_array_equal(got.asnumpy(), want)

    def test_array_accepts_order(self):
        got = np.array(self.X, order="F")
        onp.testing.assert_array_equal(got.asnumpy(), self.X)
        with pytest.raises(mx.MXNetError, match="order"):
            np.array(self.X, order="Z")


class TestOutWithAutograd:
    def test_out_keeps_grad_attachment(self):
        """out= into an attach_grad'ed buffer outside record() must keep
        the attachment, like a plain buf[:] = write does."""
        from incubator_mxnet_tpu import autograd as ag
        a = np.array(B)
        buf = np.zeros(B.shape)
        buf.attach_grad()
        np.add(a, a, out=buf)            # not recording
        with ag.record():
            s = (buf * buf).sum()
        s.backward()
        onp.testing.assert_allclose(buf.grad.asnumpy(), 2 * (B + B),
                                    rtol=1e-6)

    def test_out_write_is_recorded(self):
        """The in-place out= write must behave like the eager in-place
        ops: usable mid-training without corrupting the tape."""
        from incubator_mxnet_tpu import autograd as ag
        x = np.array(B)
        x.attach_grad()
        buf = np.zeros(B.shape)
        with ag.record():
            y = np.multiply(x, x, out=buf)
            s = y.sum()
        s.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), 2 * B, rtol=1e-6)
