"""Elastic-resume drill: a ``parallel``/ZeRO-1 training run checkpointed
at one device count and resumed at another, in real subprocesses (the
only way to change ``jax.device_count()``), through the
``parallel.distributed`` bootstrap.

The contract drilled is the one shard-count-agnostic ZeRO-1 checkpoints
actually guarantee (docs/robustness.md):

* the blob stores the portable per-leaf layout, so params AND the
  materialized optimizer states restored on an 8-device mesh are
  **bit-identical** to what the 4-device run saved;
* the elastic resume is deterministic: two independent resumes at the
  new count land on bit-identical final params;
* dropping the optimizer states (params-only restore) visibly diverges
  — i.e. the state round-trip is load-bearing, not vacuous.

(Full-run bit parity ACROSS device counts is deliberately not asserted:
a data-parallel gradient reduction over 4 shards and over 8 shards are
different float summation orders — last-ulp drift is physics, not a
bug.)
"""
import hashlib
import json
import os
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one script, three modes: the donor trains 4 batches at one device
# count and checkpoints; a resumer restores at ANOTHER count and trains
# 4 more.  Every mode writes final params + a digest of the trainer-state
# blob so the test process can compare bitwise across subprocesses.
_SCRIPT = r"""
import hashlib, json, sys
import numpy as np
mode, ndev_want, ckdir, out = (sys.argv[1], int(sys.argv[2]),
                               sys.argv[3], sys.argv[4])
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.checkpoint import AsyncCheckpointer
from incubator_mxnet_tpu.gluon import loss as gloss, nn
from incubator_mxnet_tpu.parallel import distributed
from incubator_mxnet_tpu.parallel.loop import CompiledLoop

distributed.initialize()                # single-host member: no-op join
assert distributed.global_device_count() == ndev_want, \
    (distributed.global_device_count(), ndev_want)

rng = np.random.default_rng(0)
data = [(rng.standard_normal((8, 8)).astype(np.float32),
         rng.standard_normal((8, 4)).astype(np.float32)) for _ in range(8)]
mx.random.seed(11)
net = nn.HybridSequential(prefix="el_")
with net.name_scope():
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(4, in_units=16))
net.initialize(init=mx.init.Xavier())
mesh = parallel.make_mesh({"data": distributed.global_device_count()})
loop = CompiledLoop(net, gloss.L2Loss(), "sgd",
                    {"learning_rate": 0.05, "momentum": 0.9},
                    loop_steps=2, mesh=mesh, zero1=True)
if mode == "donor":
    losses = loop.run(data[:4], prefetch=False)
    ck = AsyncCheckpointer(ckdir)
    ck.save_sync(4, dict(loop.params), trainer=loop, epoch=0)
else:                                   # resume / resume2 / coldopt
    ck = AsyncCheckpointer(ckdir)
    if mode == "coldopt":
        step = ck.restore_into(params=net.collect_params())  # no trainer
        assert step == 4, step
        loop.reload_params()
    else:
        step = ck.restore_into(params=net.collect_params(), trainer=loop)
        assert step == 4, step
        loop.reload_params()
    restored = {n: np.asarray(v) for n, v in loop.params.items()}
    np.savez(out + ".restored.npz", **restored)
    losses = loop.run(data[4:], prefetch=False)
assert np.isfinite(np.asarray(losses)).all()
state_digest = hashlib.sha256(loop.get_states()).hexdigest()
np.savez(out, **{n: np.asarray(v) for n, v in loop.params.items()})
with open(out + ".meta.json", "w") as f:
    json.dump({"ndev": distributed.global_device_count(),
               "state_digest": state_digest,
               "losses": [float(x) for x in np.asarray(losses)]}, f)
print("OK", mode, distributed.global_device_count())
"""


def _run(mode, ndev, ckdir, out):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                              ""))
    proc = subprocess.run([sys.executable, "-c", _SCRIPT, mode, str(ndev),
                           ckdir, out],
                          env=env, cwd=_REPO, capture_output=True,
                          text=True, timeout=420)
    assert proc.returncode == 0, \
        f"{mode}@{ndev} failed:\n{proc.stdout}\n{proc.stderr}"
    meta = json.load(open(out + ".meta.json"))
    return dict(np.load(out)), meta


def test_elastic_resume_across_device_counts(tmp_path):
    ck = str(tmp_path / "ck")
    donor, donor_meta = _run("donor", 4, ck, str(tmp_path / "donor.npz"))

    resume, meta_a = _run("resume", 8, ck, str(tmp_path / "resume.npz"))
    # shard-count-agnostic restore: what the 8-device process rehydrates
    # is bit-identical to what the 4-device process saved — params AND
    # the materialized optimizer-state blob
    restored = dict(np.load(str(tmp_path / "resume.npz.restored.npz")))
    assert set(restored) == set(donor)
    for name in donor:
        assert np.array_equal(restored[name], donor[name]), name
    # and the resumed run actually advanced past the restored state
    assert meta_a["state_digest"] != donor_meta["state_digest"]

    # deterministic elastic resume: a second independent resume at the
    # new count lands on bit-identical final params and states
    resume2, meta_b = _run("resume", 8, ck, str(tmp_path / "resume2.npz"))
    for name in resume:
        assert np.array_equal(resume[name], resume2[name]), name
    assert meta_a["state_digest"] == meta_b["state_digest"]
    assert meta_a["losses"] == meta_b["losses"]

    # the optimizer-state round-trip is load-bearing: restoring params
    # but NOT the trainer states (fresh momentum) must diverge
    cold, _ = _run("coldopt", 8, ck, str(tmp_path / "cold.npz"))
    assert any(not np.array_equal(cold[name], resume[name])
               for name in resume), \
        "params-only resume matched the stateful resume — the " \
        "momentum round-trip is not being exercised"


def test_state_blob_digest_is_deterministic(tmp_path):
    """Cheap non-subprocess guard: the serialized trainer-state blob is
    byte-stable for an unchanged loop (the digest comparison above
    depends on it)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.gluon import loss as gloss, nn
    from incubator_mxnet_tpu.parallel.loop import CompiledLoop
    rng = np.random.default_rng(0)
    data = [(rng.standard_normal((8, 8)).astype(np.float32),
             rng.standard_normal((8, 4)).astype(np.float32))
            for _ in range(2)]
    mx.random.seed(11)
    net = nn.HybridSequential(prefix="eld_")
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8, activation="relu"))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(init=mx.init.Xavier())
    loop = CompiledLoop(net, gloss.L2Loss(), "sgd",
                        {"learning_rate": 0.05, "momentum": 0.9},
                        loop_steps=2,
                        mesh=parallel.make_mesh({"data": 8}), zero1=True)
    loop.run(data, prefetch=False)
    a = hashlib.sha256(loop.get_states()).hexdigest()
    b = hashlib.sha256(loop.get_states()).hexdigest()
    assert a == b
