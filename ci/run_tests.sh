#!/usr/bin/env bash
# Canonical "how to run everything" script (reference analog:
# ci/docker/runtime_functions.sh).  All suites run on a virtual
# 8-device CPU mesh unless a TPU tier is requested.
set -euo pipefail
cd "$(dirname "$0")/.."

usage() {
    cat <<EOF
usage: ci/run_tests.sh <function>
  unittest_cpu          full CPU suite (single run; ~30 min on 1 core)
  unittest_cpu_chunked  CPU suite in two halves (for constrained runners)
  unittest_tpu          TPU tier (tests_tpu/: op sweep on the live chip
                        + CPU-vs-TPU consistency; self-skips without one)
  smoke                 60-second end-to-end slice (gluon MNIST)
  telemetry_smoke       MNIST slice under MXNET_TELEMETRY=1; asserts the
                        Prometheus dump has nonzero op/step/compile counters
  trace_smoke           MNIST slice with the profiler+tracer on; asserts the
                        chrome trace is valid JSON with NESTED ph:"X" spans
                        and the snapshot reports a finite mfu > 0
  bench                 judged benchmark (prints one JSON line; includes a
                        telemetry snapshot when MXNET_TELEMETRY=1)
  fused_smoke           fused-optimizer drill: short training run under
                        telemetry; asserts ONE optimizer dispatch per
                        step, fused_updates == steps, and the fused jit
                        cache stops missing after warmup
  loop_smoke            whole-step capture drill: CompiledLoop run with a
                        slow (sleeping) batch source behind the device
                        prefetcher; asserts ONE dispatch per k-step
                        chunk (loop jit cache), chunk/step counters,
                        and that the trace shows fetch+h2d overlapped
                        compute (prefetch.wait << loop.chunk time)
  zero1_smoke           ZeRO-1 drill: short training run on the
                        8-virtual-device dp mesh with zero1=1; asserts
                        params bit-identical to the replicated fused
                        golden, ONE dispatch per step (zero1 jit cache
                        stops missing after warmup), the state-bytes
                        gauge at ~1/8 of the replicated gauge, and a
                        nonzero all-gather volume gauge
  fault_smoke           resilience drill: tiny run with an injected
                        transient kvstore fault, a mid-run kill (exit 17)
                        and a checkpoint resume; asserts retries > 0, the
                        resumed params are bit-identical to an
                        uninterrupted golden run, and losses stay
                        continuous across the kill
  serve_smoke           serving drill: in-process ModelServer, concurrent
                        HTTP clients; asserts batched dispatches << request
                        count, per-request outputs match the direct engine,
                        serve histograms on /metrics, and a clean drain
  obs_smoke             observability drill: 16 traced clients against a
                        server with a serving.infer:hang fault; asserts
                        every response (200 and 5xx) echoed its
                        x-request-id, the watchdog's flight-recorder dump
                        names the hung requests' ids, /slo reports the
                        budget burn, and mxtpu_slo_* series are on
                        /metrics
  generate_smoke        continuous-batching drill: staggered streaming
                        clients against a GenerationEngine model; asserts
                        the late request emits tokens BEFORE the first
                        finishes (mid-flight join), streamed outputs are
                        token-identical to solo decode, X-Request-Id
                        rides the SSE headers, a serving.infer:hang
                        during decode fails the rider (id on the error
                        event) and recovers via the watchdog, and
                        mxtpu_generate_* series are on /metrics
  spec_smoke            speculative-decoding drill: 16 streaming clients
                        against a preloaded paged target+draft server with
                        MXNET_SPEC_K=4; asserts every stream is
                        bit-identical to a no-draft golden run,
                        mxtpu_spec_accepted_tokens_per_dispatch > 1.0 on
                        /metrics, and a serving.infer:hang wedged
                        mid-verify fails its riders with ids on the
                        terminal SSE error and recovers via the watchdog
  decode_scan_smoke     scanned decode-burst drill: 16 streaming clients
                        through a router over a preloaded replica with
                        default MXNET_DECODE_SCAN_STEPS=8; asserts every
                        stream is bit-identical to a no-scan golden run,
                        the router-federated mxtpu_dispatches_per_token
                        reads < 0.2, and a serving.infer:hang wedged
                        mid-burst fails its rider (id on the terminal
                        SSE error) and recovers via the watchdog
  sampling_smoke        sampling-plane drill: 16 streaming sampled
                        clients through a router over a preloaded
                        burst replica — every done event echoes its
                        seed, two identical-seed requests are
                        byte-identical, a stop sequence completed
                        mid-burst trims the over-generated tail, and
                        sampled speculative decoding is bit-identical
                        to the no-draft run with the
                        mxtpu_spec_accept_rate{mode="sampled"} gauge
                        federated on the router /metrics
  paged_smoke           paged KV-cache drill: under an EQUAL cache-byte
                        budget (dense 4x128 positions == paged 32x16
                        blocks), 16 streaming clients with a shared
                        32-token system prompt; asserts every paged
                        stream is token-identical to dense solo decode,
                        paged sustains >= 2x the dense concurrent
                        slots, prefix-cache hits > 0 with the kv/prefix
                        series on /metrics, and a child server drains
                        in-flight streams cleanly on SIGTERM (exit 0)
  lifecycle_smoke       lifecycle drill (three parts): SIGTERM a serving
                        child under 16 concurrent clients — zero reset
                        connections, /readyz flips 503 before the port
                        closes, clean exit 0; a serving.infer:hang fault
                        trips the watchdog + breaker and recovers to
                        SERVING without a process restart; SIGTERM a
                        training loop — emergency checkpoint at the step
                        boundary, resume bit-identical to golden
  router_smoke          fleet drill (four parts): a fresh
                        MXNET_COMPILE_CACHE_DIR makes a second replica's
                        warmup-to-first-200 >= 1.5x faster; SIGKILL one
                        of 3 replicas under 16 streaming clients — zero
                        failed requests (zero-token deaths fail over
                        transparently, mid-stream deaths end in a loud
                        terminal SSE error the client re-issues);
                        rolling drain/restart of all 3 replicas — zero
                        downtime, zero mid-stream errors; prefix-affine
                        routing beats random placement on fleet-wide
                        mxtpu_prefix_cache_hits
  autoscale_smoke       self-healing fleet drill (two parts): the
                        supervisor's replica is SIGKILLed — restart
                        with exponential backoff, counted in
                        mxtpu_supervise_restarts, then quarantined
                        (flap breaker) with an incident bundle on the
                        third kill; a supervised fleet rides a diurnal
                        load curve 1→4→1 while a chaos thread SIGKILLs
                        random replicas — zero failed client requests,
                        every scale-down routed through the router's
                        drain, mxtpu_supervise_*/mxtpu_autoscale_*
                        series on the router /metrics
  fleet_obs_smoke       observability drill: 3 telemetry-enabled
                        replicas + router, 16 streaming clients, a
                        serving.infer:hang wedge on one replica —
                        stitched GET /trace shows both failover legs
                        with the surviving replica's spans grafted
                        under their hop; federated /metrics fleet sums
                        equal the arithmetic sum of replica counters;
                        exactly ONE incident bundle written, naming the
                        request ids that failed on the hung replica
  device_obs_smoke      device-plane drill: 3 replicas (one with an
                        attached draft model) + router under 16
                        streaming clients — mxtpu_dispatches_per_token
                        reads exactly 1.0 on the plain replicas and
                        < 1.0 on the spec replica; GET /programs
                        fan-out shows compiled == expected on every
                        replica; federated kv:gen owner bytes on the
                        router /metrics; one POST /debug/profile
                        fan-out returns an artifact per replica
  health_smoke          health-plane drill (three parts): a golden
                        poisoned run plane-OFF (skip guard eats an
                        injected gradient NaN), the same run under
                        MXNET_HEALTH_PLANE=1 — the detector names the
                        first non-finite leaf at the exact poisoned
                        step and the flight recorder writes exactly
                        ONE debounced training_anomaly dump carrying
                        the attribution — then a bit-identical param
                        compare across the two runs
  multichip_dryrun      8-virtual-device full-train-step compile+run
  static                mxtpu-lint static analysis (host-sync, donation,
                        closed-program-set, lock-discipline,
                        registry-drift; see docs/static_analysis.md)
                        plus the numpy-API audit — fails on any
                        unsuppressed finding
EOF
    exit 1
}

static() {
    # stdlib-only: runs without jax. Lint first (includes the
    # code<->docs registry-drift pass), then the numpy surface audit.
    python tools/mxtpu_lint.py incubator_mxnet_tpu
    python tools/np_audit.py --check
}

unittest_cpu() {
    python -m pytest tests/ -q
}

unittest_cpu_chunked() {
    mapfile -t files < <(ls tests/test_*.py | sort)
    half=$(( (${#files[@]} + 1) / 2 ))
    python -m pytest "${files[@]:0:half}" -q -p no:cacheprovider
    python -m pytest "${files[@]:half}" -q -p no:cacheprovider
}

unittest_tpu() {
    python -m pytest tests_tpu/ -q
}

smoke() {
    python example/gluon/mnist.py --cpu --epochs 1
}

telemetry_smoke() {
    local dump=/tmp/mxtpu_telemetry_smoke.prom
    rm -f "$dump"
    MXNET_TELEMETRY=1 MXNET_TELEMETRY_DUMP="$dump" \
        python example/gluon/mnist.py --cpu --epochs 1 --hybridize
    python - "$dump" <<'EOF'
import sys

vals = {}
for line in open(sys.argv[1]):
    line = line.strip()
    if not line or line.startswith("#"):
        continue
    name, _, val = line.rpartition(" ")
    base = name.split("{")[0]
    try:
        vals[base] = vals.get(base, 0.0) + float(val)
    except ValueError:
        pass

for metric in ("mx_op_dispatch_total", "mx_trainer_steps_total",
               "mx_compile_total", "mx_trainer_step_seconds_count"):
    assert vals.get(metric, 0) > 0, \
        f"telemetry_smoke: {metric} is zero/absent; got {sorted(vals)}"
print("telemetry_smoke ok:",
      {k: vals[k] for k in ("mx_op_dispatch_total",
                            "mx_trainer_steps_total", "mx_compile_total")})
EOF
}

trace_smoke() {
    local trace=/tmp/mxtpu_trace_smoke.json
    local snap=/tmp/mxtpu_trace_smoke_snapshot.json
    rm -f "$trace" "$snap"
    TRACE_OUT="$trace" SNAP_OUT="$snap" python - <<'EOF'
import json, os, runpy, sys

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry

telemetry.start()
mx.profiler.set_config(filename=os.environ["TRACE_OUT"])
mx.profiler.set_state("run")
sys.argv = ["mnist.py", "--cpu", "--epochs", "1", "--hybridize"]
runpy.run_path("example/gluon/mnist.py", run_name="__main__")
mx.profiler.set_state("stop")
mx.profiler.dump()
with open(os.environ["SNAP_OUT"], "w") as f:
    json.dump(telemetry.snapshot(include_memory=False), f)
EOF
    python - "$trace" "$snap" <<'EOF'
import json, math, sys

trace = json.load(open(sys.argv[1]))          # must be valid JSON
spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
assert spans, "trace_smoke: no ph:X events at all"

def contains(outer, inner):
    return (outer is not inner
            and outer.get("pid") == inner.get("pid")
            and outer.get("tid") == inner.get("tid")
            and outer["ts"] <= inner["ts"]
            and inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"])

nested = [(o["name"], i["name"]) for o in spans for i in spans
          if contains(o, i)]
assert nested, "trace_smoke: no nested ph:X spans in the trace"

snap = json.load(open(sys.argv[2]))
mfu = snap["gauges"].get("mxtpu_mfu")
assert mfu is not None and math.isfinite(mfu) and mfu > 0, \
    f"trace_smoke: mfu not finite/positive: {mfu!r}"
assert snap["histograms"]["mxtpu_step_seconds"]["count"] > 0
print("trace_smoke ok: %d spans, %d nestings, mfu=%.3g"
      % (len(spans), len(nested), mfu))
EOF
}

bench() {
    python bench.py
}

fused_smoke() {
    JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd as ag
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.gluon import Trainer, nn

telemetry.start()
mx.random.seed(0)
net = nn.HybridSequential()
for _ in range(3):
    net.add(nn.Dense(32, in_units=32, activation="relu"))
net.initialize(init=mx.init.Xavier())
net.hybridize()
x = mx.nd.array(np.random.default_rng(0).standard_normal(
    (8, 32)).astype(np.float32))

trainer = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-3})
STEPS = 6
for _ in range(STEPS):
    with ag.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    trainer.step(8)
mx.nd.waitall()

assert trainer._fused is not None, \
    "fused_smoke: fused updater not engaged (default path regressed)"
flat = telemetry.counters_flat()
fused = flat.get("mxtpu_optimizer_fused_updates", 0)
assert fused == STEPS, \
    f"fused_smoke: fused_updates {fused} != steps {STEPS}"
g = telemetry.registry.get("mxtpu_optimizer_dispatches_per_step")
disp = sum(g._values.values())
assert disp == 1, \
    f"fused_smoke: {disp} optimizer dispatches in last step (wanted 1)"
key = (("site", "fused_update"),)
hits = telemetry.registry.get(
    "mx_compile_cache_hits_total")._values.get(key, 0)
miss = telemetry.registry.get(
    "mx_compile_cache_misses_total")._values.get(key, 0)
assert 1 <= miss <= 2 and hits + miss == STEPS, \
    f"fused_smoke: compile cache hits={hits} misses={miss} (steps {STEPS})"
print(f"fused_smoke ok: {STEPS} steps, 1 dispatch/step, "
      f"fused_updates={int(fused)}, cache hits={int(hits)} "
      f"misses={int(miss)}")
EOF
}

loop_smoke() {
    local trace=/tmp/mxtpu_loop_smoke_trace.json
    rm -f "$trace"
    TRACE_OUT="$trace" JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import time

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.gluon import loss as gloss, nn
from incubator_mxnet_tpu.io.prefetch import DevicePrefetcher
from incubator_mxnet_tpu.parallel import CompiledLoop, make_mesh

telemetry.start()
mx.profiler.set_config(filename=os.environ["TRACE_OUT"])
mx.profiler.set_state("run")

mx.random.seed(0)
net = nn.HybridSequential()
net.add(nn.Dense(1024, in_units=1024, activation="relu"))
net.add(nn.Dense(1024, in_units=1024, activation="relu"))
net.add(nn.Dense(1024, in_units=1024))
net.initialize(init=mx.init.Xavier())

K, STEPS = 4, 12
loop = CompiledLoop(net, gloss.L2Loss(), "sgd",
                    {"learning_rate": 0.01, "momentum": 0.9},
                    loop_steps=K, mesh=make_mesh({"data": 1}))

rng = np.random.default_rng(0)
def batches():
    for _ in range(STEPS):
        time.sleep(0.003)        # a deliberately slow host-side source
        yield (rng.standard_normal((64, 1024)).astype(np.float32),
               rng.standard_normal((64, 1024)).astype(np.float32))

pf = DevicePrefetcher(batches(), placement=loop._shard_batch)
t0 = time.perf_counter()
losses = loop.run(pf)            # run() keeps an existing prefetcher
wall = time.perf_counter() - t0
st = pf.stats()

mx.profiler.set_state("stop")
mx.profiler.dump()

assert losses.shape == (STEPS,) and np.isfinite(losses).all(), losses
flat = telemetry.counters_flat()
chunks = flat.get("mxtpu_loop_chunks", 0)
assert chunks == STEPS // K, f"loop_smoke: {chunks} chunks (wanted 3)"
assert flat.get("mx_trainer_steps_total", 0) == STEPS
key = (("site", "loop"),)
hits = telemetry.registry.get(
    "mx_compile_cache_hits_total")._values.get(key, 0)
miss = telemetry.registry.get(
    "mx_compile_cache_misses_total")._values.get(key, 0)
assert miss == 1 and hits + miss == chunks, \
    f"loop_smoke: hits={hits} misses={miss} for {chunks} chunks — " \
    "wanted ONE compiled dispatch per k-step chunk"
assert not st["degraded"] and st["batches"] == STEPS

# overlap: the consumer barely waited for fetch+h2d even though every
# upstream batch slept — the pipeline hid it behind chunk compute
assert st["wait_seconds"] < 0.5 * wall, \
    f"loop_smoke: consumer waited {st['wait_seconds']:.3f}s " \
    f"of {wall:.3f}s — prefetch is not overlapping"

# same fact in the span trace: prefetch.wait time between chunk spans
# is a small fraction of chunk time (no fetch-wait gap)
trace = json.load(open(os.environ["TRACE_OUT"]))
spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
dur = {}
for e in spans:
    dur[e["name"]] = dur.get(e["name"], 0.0) + e["dur"]
assert dur.get("loop.chunk", 0) > 0, sorted(dur)
warm = max((e["dur"] for e in spans if e["name"] == "prefetch.wait"),
           default=0.0)          # first wait overlaps chunk-0 compile
steady = dur.get("prefetch.wait", 0.0) - warm
assert steady < 0.5 * dur["loop.chunk"], \
    f"loop_smoke: steady-state prefetch.wait {steady / 1e6:.3f}s vs " \
    f"loop.chunk {dur['loop.chunk'] / 1e6:.3f}s — fetch-wait gap visible"

telemetry.stop()
print(f"loop_smoke ok: {STEPS} steps in {chunks} dispatches "
      f"(hits={int(hits)} misses={int(miss)}), consumer waited "
      f"{st['wait_seconds']:.3f}s of {wall:.3f}s, steady prefetch.wait "
      f"{steady / 1e6:.3f}s vs chunk {dur['loop.chunk'] / 1e6:.3f}s")
EOF
}

zero1_smoke() {
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd as ag
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.gluon import Trainer, nn

STEPS = 6

def train(zero1):
    mx.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(3):
        net.add(nn.Dense(64, in_units=64, activation="relu"))
    net.initialize(init=mx.init.Xavier())
    x = mx.nd.array(np.random.default_rng(0).standard_normal(
        (8, 64)).astype(np.float32))
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-3, "wd": 1e-2},
                      fused=True, zero1=zero1)
    for _ in range(STEPS):
        with ag.record():
            loss = (net(x) ** 2).mean()
        loss.backward()
        trainer.step(8)
    mx.nd.waitall()
    params = [p.data().asnumpy()
              for p in net.collect_params().values()]
    return params, trainer

# golden: the replicated fused path (also records the full state bytes)
telemetry.start()
golden, _ = train(zero1=False)
full_bytes = telemetry.counters_flat()["mxtpu_optimizer_state_bytes"]
telemetry.stop()
telemetry.reset()

telemetry.start()
sharded, trainer = train(zero1=True)
assert trainer._fused is not None and trainer._fused._z_mesh is not None, \
    "zero1_smoke: zero1 fused updater not engaged"
assert trainer._fused._z_state is not None, \
    "zero1_smoke: flat sharded state never materialized"
n_dev = int(trainer._fused._z_mesh.shape["data"])
assert n_dev == 8, f"zero1_smoke: dp mesh has {n_dev} devices (wanted 8)"

# 1. bit parity with the replicated golden
for a, b in zip(sharded, golden):
    assert np.array_equal(a, b), \
        "zero1_smoke: sharded params diverged from the replicated golden"

# 2. still ONE donated dispatch per step, compiled once
flat = telemetry.counters_flat()
assert flat["mxtpu_optimizer_fused_updates"] == STEPS
g = telemetry.registry.get("mxtpu_optimizer_dispatches_per_step")
disp = sum(g._values.values())
assert disp == 1, \
    f"zero1_smoke: {disp} optimizer dispatches in last step (wanted 1)"
key = (("site", "zero1_update"),)
hits = telemetry.registry.get(
    "mx_compile_cache_hits_total")._values.get(key, 0)
miss = telemetry.registry.get(
    "mx_compile_cache_misses_total")._values.get(key, 0)
assert 1 <= miss <= 2 and hits + miss == STEPS, \
    f"zero1_smoke: compile cache hits={hits} misses={miss} (steps {STEPS})"

# 3. the memory win: per-replica state bytes ~1/8 of replicated
shard_bytes = flat["mxtpu_optimizer_state_bytes"]
ratio = shard_bytes / full_bytes
assert ratio <= 0.25, \
    f"zero1_smoke: state ratio {ratio:.3f} > 0.25 " \
    f"({int(shard_bytes)}/{int(full_bytes)} bytes)"
assert shard_bytes * n_dev >= full_bytes, \
    "zero1_smoke: state gauge below 1/N — accounting is wrong"
ag_bytes = flat["mxtpu_zero1_allgather_bytes"]
assert ag_bytes > 0, "zero1_smoke: all-gather volume gauge not set"

print(f"zero1_smoke ok: {STEPS} steps bit-identical to golden, "
      f"1 dispatch/step (hits={int(hits)} misses={int(miss)}), "
      f"state {int(shard_bytes)}/{int(full_bytes)} bytes "
      f"(ratio {ratio:.3f}), allgather {int(ag_bytes)} B/step")
EOF
}

fault_smoke() {
    local out=/tmp/mxtpu_fault_smoke
    rm -rf "$out"
    local plan="kvstore.push:ioerror@2"
    # golden: no faults, no kill — the reference trajectory
    env -u MXNET_FAULT_PLAN python tools/fault_smoke.py golden --out "$out"
    # kill: same run under an injected transient fault, preempted mid-run
    set +e
    MXNET_FAULT_PLAN="$plan" python tools/fault_smoke.py kill --out "$out"
    local rc=$?
    set -e
    [ "$rc" -eq 17 ] || {
        echo "fault_smoke: kill run exited $rc (wanted 17)"; exit 1; }
    # resume: restore the checkpoint, absorb the fault again, finish
    MXNET_FAULT_PLAN="$plan" python tools/fault_smoke.py resume --out "$out"
    # check: bit-identical params, continuous losses
    env -u MXNET_FAULT_PLAN python tools/fault_smoke.py check --out "$out"
}

serve_smoke() {
    JAX_PLATFORMS=cpu python - <<'EOF'
import json
import threading
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.serving import InferenceEngine, ModelServer
from incubator_mxnet_tpu.serving import metrics as smetrics

telemetry.start()
mx.random.seed(0)
net = nn.HybridSequential()
for _ in range(3):
    net.add(nn.Dense(64, in_units=64, activation="relu"))
net.initialize(init=mx.init.Xavier())

CLIENTS, REQS = 16, 4
engine = InferenceEngine.from_block(net, [(64,)], name="smoke",
                                    max_batch_size=CLIENTS)
rng = np.random.default_rng(0)
xs = [rng.standard_normal((1, 64)).astype(np.float32)
      for _ in range(CLIENTS)]
refs = [np.asarray(engine.predict([x])[0]) for x in xs]

srv = ModelServer(port=0, max_delay_ms=10.0)
srv.add_model("smoke", engine, warmup=True)
srv.start()
url = f"http://127.0.0.1:{srv.port}"
req0, bat0 = smetrics.REQUESTS.value, smetrics.BATCHES.value

errors = []
def client(i):
    try:
        body = json.dumps({"inputs": [xs[i].tolist()]}).encode()
        for _ in range(REQS):
            r = urllib.request.urlopen(urllib.request.Request(
                url + "/v1/models/smoke:predict", data=body), timeout=30)
            out = np.array(json.loads(r.read())["outputs"][0],
                           dtype=np.float32)
            np.testing.assert_allclose(out, refs[i], rtol=1e-4,
                                       atol=1e-5)
    except Exception as e:
        errors.append(f"client {i}: {e!r}")

threads = [threading.Thread(target=client, args=(i,))
           for i in range(CLIENTS)]
[t.start() for t in threads]
[t.join() for t in threads]
assert not errors, "serve_smoke: " + "; ".join(errors[:3])

n_req = smetrics.REQUESTS.value - req0
n_bat = smetrics.BATCHES.value - bat0
assert n_req == CLIENTS * REQS, \
    f"serve_smoke: {n_req} requests counted (wanted {CLIENTS * REQS})"
assert n_bat <= n_req / 2, \
    f"serve_smoke: {int(n_bat)} batches for {int(n_req)} requests — " \
    "dynamic batching is not coalescing"
prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
for series in ("mxtpu_serve_batch_size", "mxtpu_serve_queue_wait_seconds",
               "mxtpu_serve_latency_seconds"):
    assert series in prom, f"serve_smoke: {series} missing from /metrics"
assert engine.compiled_programs() == len(engine.buckets), \
    f"serve_smoke: {engine.compiled_programs()} compiled programs for " \
    f"{len(engine.buckets)} buckets — the jit cache is not bounded"
srv.stop()                      # graceful drain + port release
assert srv.models() == [], "serve_smoke: registry not empty after stop"
print(f"serve_smoke ok: {int(n_req)} requests in {int(n_bat)} batches "
      f"(mean {n_req / n_bat:.1f} rows), "
      f"{engine.compiled_programs()} programs for "
      f"{len(engine.buckets)} buckets, clean shutdown")
EOF
}

obs_smoke() {
    local out=/tmp/mxtpu_obs_smoke
    rm -rf "$out" && mkdir -p "$out"
    MXNET_FAULT_PLAN="serving.infer:hang:30@1" \
    MXNET_SERVE_HANG_SECONDS=0.5 \
    MXNET_SERVE_BREAKER_COOLDOWN_SECONDS=0.3 \
    MXNET_SERVE_SLO_P99_MS=250 \
    MXNET_SERVE_SLO_AVAILABILITY=0.99 \
    MXNET_FLIGHT_DUMP_DIR="$out" \
    JAX_PLATFORMS=cpu python - <<'EOF'
import glob
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.serving import InferenceEngine, ModelServer

telemetry.start()
mx.random.seed(0)
net = nn.HybridSequential()
for _ in range(2):
    net.add(nn.Dense(32, in_units=32, activation="relu"))
net.initialize(init=mx.init.Xavier())

CLIENTS, REQS = 16, 3
engine = InferenceEngine.from_block(net, [(32,)], name="obs",
                                    max_batch_size=CLIENTS)
srv = ModelServer(port=0, max_delay_ms=10.0)
srv.add_model("obs", engine, warmup=True)
srv.start()
url = f"http://127.0.0.1:{srv.port}"

rng = np.random.default_rng(0)
xs = [rng.standard_normal((1, 32)).astype(np.float32)
      for _ in range(CLIENTS)]

# (sent_rid, status, echoed_header_rid) per response — including 5xx
results = []
res_lock = threading.Lock()

def client(i):
    body = json.dumps({"inputs": [xs[i].tolist()]}).encode()
    for k in range(REQS):
        rid = f"obs-{i}-{k}"
        req = urllib.request.Request(
            url + "/v1/models/obs:predict", data=body,
            headers={"x-request-id": rid})
        try:
            r = urllib.request.urlopen(req, timeout=30)
            status, echoed = r.status, r.headers.get("X-Request-Id")
            r.read()
        except urllib.error.HTTPError as e:
            status, echoed = e.code, e.headers.get("X-Request-Id")
            e.read()
        with res_lock:
            results.append((rid, status, echoed))
        time.sleep(0.05)        # let the breaker cooldown recover

threads = [threading.Thread(target=client, args=(i,))
           for i in range(CLIENTS)]
[t.start() for t in threads]
[t.join() for t in threads]

# recovery round: wait out the breaker cooldown, then probe until the
# model serves again (proves the restart actually healed the worker)
recovered = []
deadline = time.monotonic() + 10.0
k = 0
while time.monotonic() < deadline and not recovered:
    time.sleep(0.2)
    rid = f"obs-recover-{k}"
    k += 1
    req = urllib.request.Request(
        url + "/v1/models/obs:predict",
        data=json.dumps({"inputs": [xs[0].tolist()]}).encode(),
        headers={"x-request-id": rid})
    try:
        r = urllib.request.urlopen(req, timeout=30)
        status, echoed = r.status, r.headers.get("X-Request-Id")
        r.read()
    except urllib.error.HTTPError as e:
        status, echoed = e.code, e.headers.get("X-Request-Id")
        e.read()
    results.append((rid, status, echoed))
    if status == 200:
        recovered.append(rid)

# 1. every response, 200 and 5xx alike, echoed its x-request-id
assert len(results) >= CLIENTS * REQS
bad_echo = [(rid, st, ech) for rid, st, ech in results if ech != rid]
assert not bad_echo, f"obs_smoke: responses without echo: {bad_echo[:3]}"
failed = [rid for rid, st, _ in results if st >= 500]
ok = [rid for rid, st, _ in results if st == 200]
assert failed, "obs_smoke: the hang fault produced no 5xx responses"
assert recovered, "obs_smoke: nothing recovered after the watchdog restart"

# 2. the watchdog wrote a flight dump naming the hung requests' ids
dump_dir = os.environ["MXNET_FLIGHT_DUMP_DIR"]
deadline = time.monotonic() + 10.0
dumps = []
while time.monotonic() < deadline:
    dumps = glob.glob(os.path.join(dump_dir,
                                   "flight_*_watchdog_restart.json"))
    if dumps:
        break
    time.sleep(0.1)
assert dumps, f"obs_smoke: no watchdog flight dump in {dump_dir}"
dump = json.load(open(dumps[0]))
wd = [e for e in dump["ring"]
      if e["type"] == "fault" and e["event"] == "watchdog"]
assert wd, "obs_smoke: no watchdog fault entry in the dump ring"
hung = [r for e in wd for r in e.get("request_ids", ())]
assert hung and set(hung) <= {rid for rid, _, _ in results}, \
    f"obs_smoke: dump names unknown request ids: {hung[:3]}"
assert set(hung) <= set(failed), \
    "obs_smoke: a request the dump calls hung got a 200"
assert "serving" in dump, "obs_smoke: dump lacks the serving provider"

# 3. /slo reports the burn
slo = json.load(urllib.request.urlopen(url + "/slo", timeout=10))
m = slo["models"]["obs"]
assert m["bad"] >= len(failed) and m["burn_rate"] > 0.0, \
    f"obs_smoke: SLO window missed the failures: {m}"

# 4. SLO series on /metrics
prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
for series in ("mxtpu_slo_error_budget_remaining", "mxtpu_slo_burn_rate",
               "mxtpu_slo_availability"):
    assert series in prom, f"obs_smoke: {series} missing from /metrics"

srv.stop()
telemetry.stop()
print(f"obs_smoke ok: {len(ok)}/{len(results)} ok, {len(failed)} failed "
      f"with ids echoed, {len(hung)} hung ids in "
      f"{os.path.basename(dumps[0])}, burn_rate={m['burn_rate']:.2f}, "
      f"budget={m['error_budget_remaining']:.2f}")
EOF
}

generate_smoke() {
    MXNET_SERVE_HANG_SECONDS=0.5 \
    MXNET_SERVE_BREAKER_COOLDOWN_SECONDS=0.3 \
    JAX_PLATFORMS=cpu python - <<'EOF'
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, telemetry
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import GenerationEngine, ModelServer

telemetry.start()
mx.random.seed(3)
net = GPTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=256, dropout=0.0)
net.initialize(init=mx.init.Normal(0.6))
net(mx.nd.array(np.zeros((1, 2), np.int32)))

engine = GenerationEngine(net, name="gen", max_slots=4, max_len=256)
LONG, LATE = [9, 9, 4, 1], [3, 7, 11]
solo_long = engine.generate(LONG, max_new_tokens=200)
solo_late = engine.generate(LATE, max_new_tokens=5)
engine.reset()

srv = ModelServer(port=0)
srv.add_model("gen", engine, warmup=True)
srv.start()
url = f"http://127.0.0.1:{srv.port}"

def stream(prompt, n, rid):
    """POST :generate with stream=true; returns (tokens-with-times,
    final events, echoed X-Request-Id header)."""
    req = urllib.request.Request(
        url + "/v1/models/gen:generate",
        data=json.dumps({"tokens": prompt, "max_new_tokens": n,
                         "stream": True}).encode(),
        headers={"x-request-id": rid})
    r = urllib.request.urlopen(req, timeout=60)
    toks, finals = [], []
    for line in r:
        line = line.strip()
        if line.startswith(b"data:"):
            d = json.loads(line.split(b":", 1)[1])
            if "token" in d:
                toks.append((d["token"], time.monotonic()))
            else:
                finals.append(d)
    return toks, finals, r.headers.get("X-Request-Id")

# -- 1. staggered streaming clients: the late request must emit tokens
#       while the first is STILL decoding (continuous admission) ------
results = {}
def run(key, prompt, n, rid):
    results[key] = stream(prompt, n, rid)

t1 = threading.Thread(target=run, args=("long", LONG, 200, "gen-long"))
t1.start()
time.sleep(0.08)
t2 = threading.Thread(target=run, args=("late", LATE, 5, "gen-late"))
t2.start()
t1.join(); t2.join()

toks_long, _, rid_long = results["long"]
toks_late, finals_late, rid_late = results["late"]
assert rid_long == "gen-long" and rid_late == "gen-late", \
    f"generate_smoke: streamed X-Request-Id not echoed: " \
    f"{rid_long!r}/{rid_late!r}"
assert [t for t, _ in toks_long] == solo_long, \
    "generate_smoke: interleaved long output != solo"
assert [t for t, _ in toks_late] == solo_late, \
    "generate_smoke: interleaved late output != solo"
assert finals_late and finals_late[-1]["request_id"] == "gen-late"
lead = toks_long[-1][1] - toks_late[0][1]
assert lead > 0, \
    "generate_smoke: late request emitted nothing before the first " \
    "request finished — no mid-flight join"

# -- 2. watchdog drill: hang the 5th decode dispatch mid-stream; the
#       rider must fail with its id on the stream, then the model
#       must recover after the restart + breaker cooldown -------------
fault.install_plan("serving.infer:hang:30@5")
toks_h, finals_h, rid_h = stream(LONG, 100, "gen-hang")
assert rid_h == "gen-hang"
assert 0 < len(toks_h) < 100, \
    f"generate_smoke: hang drill emitted {len(toks_h)} tokens"
assert finals_h and "error" in finals_h[-1], \
    f"generate_smoke: no terminal error event: {finals_h}"
assert finals_h[-1]["request_id"] == "gen-hang"
fault.clear_plan()

recovered = None
deadline = time.monotonic() + 15.0
while time.monotonic() < deadline and recovered is None:
    time.sleep(0.2)
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            url + "/v1/models/gen:generate",
            data=json.dumps({"tokens": LATE,
                             "max_new_tokens": 5}).encode()), timeout=30)
        recovered = json.loads(r.read())["tokens"]
    except urllib.error.HTTPError as e:
        e.read()                # 503 while the breaker cools down
assert recovered == solo_late, \
    f"generate_smoke: post-restart output {recovered} != solo"

# -- 3. generation series on /metrics ---------------------------------
prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
for series in ("mxtpu_generate_tokens", "mxtpu_serve_cache_slots_in_use",
               "mxtpu_generate_token_seconds",
               "mxtpu_generate_decode_step_seconds"):
    assert series in prom, f"generate_smoke: {series} missing from /metrics"

stats = json.load(urllib.request.urlopen(url + "/v1/models",
                                         timeout=10))["models"]["gen"]
assert stats["kind"] == "generation" and stats["watchdog_restarts"] == 1, stats
srv.stop()
telemetry.stop()
print(f"generate_smoke ok: late first-token led long last-token by "
      f"{lead:.3f}s, hang drill failed rider 'gen-hang' after "
      f"{len(toks_h)} tokens and recovered, "
      f"{stats['tokens_emitted']} tokens in {stats['decode_steps']} "
      f"decode steps")
EOF
}

spec_smoke() {
    MXNET_SPEC_K=4 \
    MXNET_SERVE_HANG_SECONDS=0.5 \
    MXNET_SERVE_BREAKER_COOLDOWN_SECONDS=0.3 \
    JAX_PLATFORMS=cpu python - <<'EOF'
import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, telemetry
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import GenerationEngine, ModelServer

telemetry.start()
CLIENTS, NEW = 16, 24
SYSTEM = list(range(1, 33))            # shared 32-token system prompt
PROMPTS = [SYSTEM + [40 + i % 8, i % 5] for i in range(CLIENTS)]

def build(name, seed, max_slots):
    mx.random.seed(seed)
    net = GPTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
                   num_heads=2, max_length=128, dropout=0.0)
    net.initialize(init=mx.init.Normal(0.6))
    net(mx.nd.array(np.zeros((1, 2), np.int32)))
    return GenerationEngine(net, name=name, max_slots=max_slots,
                            max_len=128, paged=True, block_size=16)

# -- golden: the SAME weights, no draft attached ----------------------
golden_eng = build("golden", 3, 1)
golden = [golden_eng.generate(p, max_new_tokens=NEW) for p in PROMPTS]
del golden_eng

# -- target + draft (identical weights => high accept rate) -----------
engine = build("gen", 3, 4)
draft = build("gen-draft", 3, 4)
engine.attach_draft(draft)             # k from MXNET_SPEC_K=4
assert engine.spec_k == 4, engine.spec_k

srv = ModelServer(port=0)
srv.add_model("gen", engine)
srv.preload()                          # all programs warm pre-bind
assert engine.warm and draft.warm, "spec_smoke: preload left a cold model"
srv.start()
url = f"http://127.0.0.1:{srv.port}"

def stream(prompt, n, rid):
    req = urllib.request.Request(
        url + "/v1/models/gen:generate",
        data=json.dumps({"tokens": prompt, "max_new_tokens": n,
                         "stream": True}).encode(),
        headers={"x-request-id": rid})
    r = urllib.request.urlopen(req, timeout=120)
    toks, finals = [], []
    for line in r:
        line = line.strip()
        if line.startswith(b"data:"):
            d = json.loads(line.split(b":", 1)[1])
            if "token" in d:
                toks.append(d["token"])
            else:
                finals.append(d)
    return toks, finals, r.headers.get("X-Request-Id")

# -- 1. 16 concurrent streaming clients, bit-identical to golden ------
results, errors = {}, []
def run(i):
    try:
        results[i] = stream(PROMPTS[i], NEW, f"spec-{i}")
    except Exception as e:
        errors.append(f"spec-{i}: {e!r}")

threads = [threading.Thread(target=run, args=(i,)) for i in range(CLIENTS)]
for t in threads:
    t.start()
    time.sleep(0.01)                   # staggered mid-flight joins
for t in threads:
    t.join()
assert not errors, "spec_smoke: " + "; ".join(errors[:3])
total_acc = total_drafted = 0
for i in range(CLIENTS):
    toks, finals, rid = results[i]
    assert rid == f"spec-{i}", f"spec_smoke: X-Request-Id lost: {rid!r}"
    assert toks == golden[i], \
        f"spec_smoke: client {i} diverged from no-draft golden: " \
        f"{toks[:8]}... != {golden[i][:8]}..."
    done = finals[-1]
    assert done["request_id"] == f"spec-{i}", done
    total_acc += done["accepted_tokens"]
    total_drafted += done["draft_tokens"]
assert total_drafted > 0 and total_acc > 0, (total_acc, total_drafted)

# -- 2. the amortization gauge must show the draft actually helping ---
prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
m = re.search(
    r'mxtpu_spec_accepted_tokens_per_dispatch\{[^}]*\}\s+([0-9.eE+-]+)',
    prom)
assert m, "spec_smoke: spec gauge missing from /metrics"
tpd = float(m.group(1))
assert tpd > 1.0, \
    f"spec_smoke: accepted_tokens_per_dispatch {tpd} <= 1.0 — the " \
    f"draft never beat plain decode"

# -- 3. wedge a verify dispatch mid-stream; riders must fail loudly
#       with their ids, then the watchdog restart must recover --------
fault.install_plan("serving.infer:hang:30@3")
toks_h, finals_h, rid_h = stream(PROMPTS[0], 100, "spec-hang")
assert rid_h == "spec-hang"
assert 0 < len(toks_h) < 100, \
    f"spec_smoke: hang drill emitted {len(toks_h)} tokens"
assert finals_h and "error" in finals_h[-1], \
    f"spec_smoke: no terminal error event: {finals_h}"
assert finals_h[-1]["request_id"] == "spec-hang"
fault.clear_plan()

recovered = None
deadline = time.monotonic() + 15.0
while time.monotonic() < deadline and recovered is None:
    time.sleep(0.2)
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            url + "/v1/models/gen:generate",
            data=json.dumps({"tokens": PROMPTS[1],
                             "max_new_tokens": NEW}).encode()), timeout=30)
        recovered = json.loads(r.read())["tokens"]
    except urllib.error.HTTPError as e:
        e.read()                       # 503 while the breaker cools down
assert recovered == golden[1], \
    f"spec_smoke: post-restart output != golden"

stats = json.load(urllib.request.urlopen(url + "/v1/models",
                                         timeout=10))["models"]["gen"]
assert stats["spec_k"] == 4 and stats["watchdog_restarts"] == 1, stats
srv.stop()
telemetry.stop()
print(f"spec_smoke ok: {CLIENTS} streams bit-identical to no-draft "
      f"golden, {tpd:.2f} accepted tokens/dispatch "
      f"(accept rate {stats['spec_accept_rate']:.2f}), hang drill "
      f"failed rider 'spec-hang' after {len(toks_h)} tokens and "
      f"recovered")
EOF
}

decode_scan_smoke() {
    MXNET_SERVE_HANG_SECONDS=0.5 \
    MXNET_SERVE_BREAKER_COOLDOWN_SECONDS=0.3 \
    JAX_PLATFORMS=cpu python - <<'EOF'
import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, telemetry
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import (GenerationEngine, ModelServer,
                                         Router)

telemetry.start()
CLIENTS, NEW = 16, 48
SYSTEM = list(range(1, 33))            # shared 32-token system prompt
PROMPTS = [SYSTEM + [40 + i % 8, i % 5] for i in range(CLIENTS)]

def build(name, max_slots, scan_steps):
    mx.random.seed(3)
    net = GPTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
                   num_heads=2, max_length=256, dropout=0.0)
    net.initialize(init=mx.init.Normal(0.6))
    net(mx.nd.array(np.zeros((1, 2), np.int32)))
    return GenerationEngine(net, name=name, max_slots=max_slots,
                            max_len=256, paged=True, block_size=16,
                            scan_steps=scan_steps)

# -- golden: the SAME weights, bursts disabled ------------------------
golden_eng = build("golden", 1, 0)
golden = [golden_eng.generate(p, max_new_tokens=NEW) for p in PROMPTS]
del golden_eng

# -- replica with the default burst depth + a router on top -----------
engine = build("gen", CLIENTS, 8)      # every client fits: steady state
assert engine.scan_steps == 8, engine.scan_steps
srv = ModelServer(port=0)
srv.add_model("gen", engine)
srv.preload()                          # burst program warm pre-bind
assert engine.warm, "decode_scan_smoke: preload left a cold model"
srv.start()
router = Router([f"127.0.0.1:{srv.port}"], port=0, host="127.0.0.1",
                health_interval=0.1, upstream_timeout=60.0,
                retry_deadline=60.0, federate_seconds=0.2)
router.start()
url = f"http://127.0.0.1:{router.port}"
direct = f"http://127.0.0.1:{srv.port}"

def stream(base, prompt, n, rid):
    req = urllib.request.Request(
        base + "/v1/models/gen:generate",
        data=json.dumps({"tokens": prompt, "max_new_tokens": n,
                         "stream": True}).encode(),
        headers={"x-request-id": rid})
    r = urllib.request.urlopen(req, timeout=180)
    toks, finals = [], []
    for line in r:
        line = line.strip()
        if line.startswith(b"data:"):
            d = json.loads(line.split(b":", 1)[1])
            if "token" in d:
                toks.append(d["token"])
            else:
                finals.append(d)
    return toks, finals, r.headers.get("X-Request-Id")

# -- 1. 16 streaming clients through the router, bit-identical --------
results, errors = {}, []
def run(i):
    try:
        results[i] = stream(url, PROMPTS[i], NEW, f"scan-{i}")
    except Exception as e:
        errors.append(f"scan-{i}: {e!r}")

threads = [threading.Thread(target=run, args=(i,)) for i in range(CLIENTS)]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert not errors, "decode_scan_smoke: " + "; ".join(errors[:3])
for i in range(CLIENTS):
    toks, finals, rid = results[i]
    assert rid == f"scan-{i}", \
        f"decode_scan_smoke: X-Request-Id lost: {rid!r}"
    assert toks == golden[i], \
        f"decode_scan_smoke: client {i} diverged from no-scan golden: " \
        f"{toks[:8]}... != {golden[i][:8]}..."
st = json.load(urllib.request.urlopen(
    direct + "/v1/models", timeout=10))["models"]["gen"]
assert st["decode_scan_steps"] == 8, st
assert st["decode_burst_dispatches"] > 0, \
    "decode_scan_smoke: no burst dispatch was ever taken"

# -- 2. router-federated dispatch economy: < 0.2 at steady state ------
router._federate_maybe(force=True)
prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
m = re.search(r'mxtpu_dispatches_per_token\{model="gen"\}'
              r'\s+([0-9.eE+-]+)', prom)
assert m, "decode_scan_smoke: dispatches-per-token not federated:\n" + \
    "\n".join(l for l in prom.splitlines() if "dispatches_per" in l)
dpt = float(m.group(1))
assert dpt < 0.2, \
    f"decode_scan_smoke: federated dispatches_per_token {dpt} >= 0.2 " \
    f"— the scan is not amortizing the host out of the token path"

# -- 3. wedge a burst dispatch mid-stream; the rider must fail loudly
#       with its id, then the watchdog restart must recover -----------
fault.install_plan("serving.infer:hang:30@3")
toks_h, finals_h, rid_h = stream(direct, PROMPTS[0], 100, "scan-hang")
assert rid_h == "scan-hang"
assert 0 < len(toks_h) < 100, \
    f"decode_scan_smoke: hang drill emitted {len(toks_h)} tokens"
assert finals_h and "error" in finals_h[-1], \
    f"decode_scan_smoke: no terminal error event: {finals_h}"
assert finals_h[-1]["request_id"] == "scan-hang"
fault.clear_plan()

recovered = None
deadline = time.monotonic() + 15.0
while time.monotonic() < deadline and recovered is None:
    time.sleep(0.2)
    try:
        r = urllib.request.urlopen(urllib.request.Request(
            direct + "/v1/models/gen:generate",
            data=json.dumps({"tokens": PROMPTS[1],
                             "max_new_tokens": NEW}).encode()), timeout=60)
        recovered = json.loads(r.read())["tokens"]
    except urllib.error.HTTPError as e:
        e.read()                       # 503 while the breaker cools down
assert recovered == golden[1], \
    "decode_scan_smoke: post-restart output != golden"
st = json.load(urllib.request.urlopen(
    direct + "/v1/models", timeout=10))["models"]["gen"]
assert st["watchdog_restarts"] == 1, st
router.stop()
srv.stop()
telemetry.stop()
print(f"decode_scan_smoke ok: {CLIENTS} streams bit-identical to "
      f"no-scan golden, federated dispatches_per_token {dpt:.3f} "
      f"(k=8), hang drill failed rider 'scan-hang' after "
      f"{len(toks_h)} tokens and recovered")
EOF
}

sampling_smoke() {
    MXNET_SPEC_K=4 \
    JAX_PLATFORMS=cpu python - <<'EOF'
import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import (GenerationEngine, ModelServer,
                                         Router, SamplingParams)

telemetry.start()
CLIENTS, NEW = 16, 24
SYSTEM = list(range(1, 17))            # shared 16-token system prompt
PROMPTS = [SYSTEM + [40 + i % 8, i % 5] for i in range(CLIENTS)]

def build(name, max_slots, scan_steps):
    mx.random.seed(3)
    net = GPTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
                   num_heads=2, max_length=128, dropout=0.0)
    net.initialize(init=mx.init.Normal(0.6))
    net(mx.nd.array(np.zeros((1, 2), np.int32)))
    return GenerationEngine(net, name=name, max_slots=max_slots,
                            max_len=128, paged=True, block_size=16,
                            scan_steps=scan_steps)

# "gen": burst replica; "spec": target+draft (identical weights) ------
gen = build("gen", CLIENTS, 8)
tgt = build("spec", 4, 0)
dr = build("spec-draft", 4, 0)
tgt.attach_draft(dr)                   # k from MXNET_SPEC_K=4
srv = ModelServer(port=0)
srv.add_model("gen", gen)
srv.add_model("spec", tgt)
srv.preload()
srv.start()
router = Router([f"127.0.0.1:{srv.port}"], port=0, host="127.0.0.1",
                health_interval=0.1, upstream_timeout=60.0,
                retry_deadline=60.0, federate_seconds=0.2)
router.start()
url = f"http://127.0.0.1:{router.port}"
direct = f"http://127.0.0.1:{srv.port}"

def post(model, body, rid=None, base=None):
    req = urllib.request.Request(
        (base or url) + f"/v1/models/{model}:generate",
        data=json.dumps(body).encode(),
        headers={"x-request-id": rid} if rid else {})
    return urllib.request.urlopen(req, timeout=120)

def stream(model, body, rid):
    r = post(model, dict(body, stream=True), rid)
    toks, finals = [], []
    for line in r:
        line = line.strip()
        if line.startswith(b"data:"):
            d = json.loads(line.split(b":", 1)[1])
            if "token" in d:
                toks.append(d["token"])
            else:
                finals.append(d)
    return toks, finals, r.headers.get("X-Request-Id")

# -- 1. 16 streaming SAMPLED clients through the router ---------------
results, errors = {}, []
def run(i):
    try:
        results[i] = stream("gen", {
            "tokens": PROMPTS[i], "max_new_tokens": NEW,
            "temperature": 0.8, "top_p": 0.9, "seed": 1000 + i},
            f"smp-{i}")
    except Exception as e:
        errors.append(f"smp-{i}: {e!r}")

threads = [threading.Thread(target=run, args=(i,)) for i in range(CLIENTS)]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert not errors, "sampling_smoke: " + "; ".join(errors[:3])
for i in range(CLIENTS):
    toks, finals, rid = results[i]
    assert rid == f"smp-{i}", f"sampling_smoke: X-Request-Id lost: {rid!r}"
    assert len(toks) == NEW and finals[-1].get("seed") == 1000 + i, \
        f"sampling_smoke: client {i} malformed: {len(toks)} toks, " \
        f"{finals[-1]}"
assert len({tuple(results[i][0]) for i in range(CLIENTS)}) > 1, \
    "sampling_smoke: every seed produced identical output"

# -- 2. two identical-seed requests are byte-identical ----------------
body0 = {"tokens": PROMPTS[0], "max_new_tokens": NEW,
         "temperature": 0.8, "top_p": 0.9, "seed": 1000}
r1 = json.loads(post("gen", body0).read())
r2 = json.loads(post("gen", body0).read())
assert r1["tokens"] == r2["tokens"] == results[0][0], \
    "sampling_smoke: identical-seed replay diverged"
assert r1["seed"] == 1000, r1

# -- 3. stop sequence completed mid-burst: tail trimmed ---------------
base = json.loads(post("gen", {"tokens": PROMPTS[1],
                               "max_new_tokens": NEW,
                               "temperature": 0.8,
                               "seed": 77}).read())["tokens"]
stopped = json.loads(post("gen", {"tokens": PROMPTS[1],
                                  "max_new_tokens": NEW,
                                  "temperature": 0.8, "seed": 77,
                                  "stop": [base[3:5]]}).read())["tokens"]
assert stopped == base[:5], \
    f"sampling_smoke: stop trim wrong: {stopped} vs {base[:5]}"
st = json.load(urllib.request.urlopen(
    direct + "/v1/models", timeout=10))["models"]["gen"]
assert st["stop_hits"] >= 1 and st["decode_burst_dispatches"] > 0, st

# -- 4. sampled spec preserves the no-draft stream; accept-rate gauge
#       carries mode="sampled" on the federated /metrics --------------
golden_eng = build("golden", 1, 0)
sp = SamplingParams(temperature=0.7, top_p=0.95, seed=4242)
want = golden_eng.generate(PROMPTS[2], NEW, sampling=sp)
got = json.loads(post("spec", {"tokens": PROMPTS[2],
                               "max_new_tokens": NEW,
                               "temperature": 0.7, "top_p": 0.95,
                               "seed": 4242}).read())
assert got["tokens"] == want, \
    f"sampling_smoke: sampled spec diverged from no-draft run: " \
    f"{got['tokens'][:8]}... != {want[:8]}..."
assert got["draft_tokens"] > 0, got
router._federate_maybe(force=True)
prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
m = re.search(r'mxtpu_spec_accept_rate\{[^}]*mode="sampled"[^}]*\}'
              r'\s+([0-9.eE+-]+)', prom)
assert m, "sampling_smoke: no mode=\"sampled\" accept-rate gauge:\n" + \
    "\n".join(l for l in prom.splitlines() if "accept_rate" in l)
rate = float(m.group(1))
assert 0.0 <= rate <= 1.0, rate
assert re.search(r'mxtpu_sample_requests\{[^}]*mode="sampled"',
                 prom), "sampling_smoke: mxtpu_sample_requests missing"
router.stop()
srv.stop()
telemetry.stop()
print(f"sampling_smoke ok: {CLIENTS} sampled streams through the "
      f"router, identical-seed replay byte-identical, stop trimmed "
      f"{st['stop_trimmed_tokens']} burst-tail tokens, sampled spec "
      f"bit-identical to no-draft (accept rate {rate:.2f})")
EOF
}

paged_smoke() {
    # child server script for the SIGTERM-drain leg
    cat > /tmp/mxtpu_paged_child.py <<'CHILD'
import sys
import numpy as np
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import (GenerationEngine, ModelServer,
                                         lifecycle)

mx.random.seed(7)
net = GPTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=128, dropout=0.0)
net.initialize(init=mx.init.Normal(0.6))
net(mx.nd.array(np.zeros((1, 2), np.int32)))
eng = GenerationEngine(net, name="gen", max_slots=8, max_len=128)
srv = ModelServer(port=0)
srv.add_model("gen", eng, warmup=True)
srv.start()
print(f"PORT {srv.port}", flush=True)
sys.exit(lifecycle.run_until_shutdown(srv))
CHILD
    JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.models.gpt import GPTModel
from incubator_mxnet_tpu.serving import (ContinuousBatcher,
                                         GenerationEngine, ModelServer)

telemetry.start()
mx.random.seed(7)
net = GPTModel(vocab_size=50, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=128, dropout=0.0)
net.initialize(init=mx.init.Normal(0.6))
net(mx.nd.array(np.zeros((1, 2), np.int32)))

# Equal cache-byte budget: dense 4 slots x 128 positions == 512
# cached token-positions == paged 32 usable blocks x 16 tokens.
SYSTEM = [7] * 32                       # shared system prompt: 2 blocks
N_CLIENTS, NEW = 16, 12


def prompt_for(i):
    return SYSTEM + [1 + (i % 40), 2 + (i % 37), 3, 4]


dense = GenerationEngine(net, name="gen", max_slots=4, max_len=128,
                         paged=False)
solo = []
for i in range(N_CLIENTS):
    solo.append(dense.generate(prompt_for(i), max_new_tokens=NEW))
    dense.reset()

# -- 1. dense concurrency under the byte budget: 16 clients share the
#       4 slots the budget buys ---------------------------------------
bat = ContinuousBatcher(dense, name="gen")
reqs = [bat.submit_async(prompt_for(i), max_new_tokens=NEW)
        for i in range(N_CLIENTS)]
for i, r in enumerate(reqs):
    assert r.result(timeout=120) == solo[i], \
        f"paged_smoke: dense batched output {i} != solo"
dense_peak = bat.stats()["peak_slots_in_use"]
bat.close()
assert dense_peak <= 4, f"paged_smoke: dense peak {dense_peak} > slots"

# -- 2. paged server, SAME byte budget: 16 streaming clients, strictly
#       more concurrent slots, prefix hits on the shared prompt -------
paged = GenerationEngine(net, name="gen", max_slots=16, max_len=128,
                         paged=True, block_size=16, num_blocks=33)
srv = ModelServer(port=0)
srv.add_model("gen", paged, warmup=True)
srv.start()
url = f"http://127.0.0.1:{srv.port}"

outs, errors = [None] * N_CLIENTS, []


def client(i):
    try:
        req = urllib.request.Request(
            url + "/v1/models/gen:generate",
            data=json.dumps({"tokens": prompt_for(i),
                             "max_new_tokens": NEW,
                             "stream": True}).encode())
        toks = []
        with urllib.request.urlopen(req, timeout=120) as r:
            for line in r:
                line = line.strip()
                if line.startswith(b"data:"):
                    d = json.loads(line.split(b":", 1)[1])
                    if "token" in d:
                        toks.append(d["token"])
        outs[i] = toks
    except Exception as e:               # noqa: BLE001
        errors.append(f"client{i}: {e!r}")


threads = [threading.Thread(target=client, args=(i,))
           for i in range(N_CLIENTS)]
[t.start() for t in threads]
[t.join(timeout=180) for t in threads]
assert not errors, f"paged_smoke: stream failures: {errors[:5]}"
for i in range(N_CLIENTS):
    assert outs[i] == solo[i], \
        f"paged_smoke: paged stream {i} != dense solo"

stats = json.load(urllib.request.urlopen(
    url + "/v1/models", timeout=10))["models"]["gen"]
paged_peak = stats["peak_slots_in_use"]
assert paged_peak > dense_peak and paged_peak >= 2 * dense_peak, \
    f"paged_smoke: paged peak {paged_peak} vs dense {dense_peak} — " \
    f"expected >= 2x under the same cache-byte budget"
assert stats["kv_paged"] and stats["prefix_cache_hits"] > 0, \
    f"paged_smoke: no prefix hits on the shared system prompt: {stats}"

prom = urllib.request.urlopen(url + "/metrics", timeout=10).read().decode()
for series in ("mxtpu_kv_blocks_in_use", "mxtpu_kv_blocks_total",
               "mxtpu_prefix_cache_hits"):
    assert series in prom, f"paged_smoke: {series} missing from /metrics"
srv.stop()

# -- 3. SIGTERM drain: a child paged server finishes in-flight streams
#       and exits 0 ----------------------------------------------------
env = dict(os.environ, MXNET_DRAIN_SECONDS="10", JAX_PLATFORMS="cpu",
           PYTHONPATH=os.getcwd())
child = subprocess.Popen([sys.executable, "/tmp/mxtpu_paged_child.py"],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, env=env, text=True)
line = child.stdout.readline().strip()
assert line.startswith("PORT "), f"paged_smoke: bad handshake {line!r}"
port = int(line.split()[1])
curl = f"http://127.0.0.1:{port}"

drained, derrors = [None] * 4, []


def drain_client(i):
    try:
        req = urllib.request.Request(
            curl + "/v1/models/gen:generate",
            data=json.dumps({"tokens": prompt_for(i),
                             "max_new_tokens": NEW,
                             "stream": True}).encode())
        toks = []
        with urllib.request.urlopen(req, timeout=60) as r:
            for line in r:
                line = line.strip()
                if line.startswith(b"data:"):
                    d = json.loads(line.split(b":", 1)[1])
                    if "token" in d:
                        toks.append(d["token"])
        drained[i] = toks
    except Exception as e:               # noqa: BLE001
        derrors.append(f"drain client{i}: {e!r}")


dthreads = [threading.Thread(target=drain_client, args=(i,))
            for i in range(4)]
[t.start() for t in dthreads]
time.sleep(0.5)                          # streams in flight
child.send_signal(signal.SIGTERM)
rc = child.wait(timeout=30)
[t.join(timeout=30) for t in dthreads]
assert rc == 0, f"paged_smoke: child exited {rc} on SIGTERM, expected 0"
assert not derrors, f"paged_smoke: drain dropped streams: {derrors}"
for i in range(4):
    assert drained[i] == solo[i], \
        f"paged_smoke: drained stream {i} truncated or wrong"

telemetry.stop()
print(f"paged_smoke ok: equal 512-token budget sustained "
      f"{paged_peak} paged vs {dense_peak} dense concurrent slots, "
      f"{stats['prefix_cache_hits']} prefix-cache hits on the shared "
      f"system prompt, SIGTERM drained 4 in-flight streams cleanly")
EOF
}

lifecycle_smoke() {
    local out=/tmp/mxtpu_lifecycle_smoke
    rm -rf "$out"
    # SIGTERM-under-load: zero dropped in-flight requests, readyz-first
    JAX_PLATFORMS=cpu python tools/lifecycle_smoke.py serve --out "$out"
    # hung-worker drill: watchdog + breaker recover in-process
    JAX_PLATFORMS=cpu python tools/lifecycle_smoke.py hang --out "$out"
    # preemption drill: cooperative SIGTERM checkpoint, exact resume
    JAX_PLATFORMS=cpu python tools/lifecycle_smoke.py train --out "$out"
}

router_smoke() {
    local cc=/tmp/mxtpu_router_smoke_cc
    rm -rf "$cc"
    JAX_PLATFORMS=cpu python tools/router_smoke.py all --cache-dir "$cc"
}

autoscale_smoke() {
    local cc=/tmp/mxtpu_autoscale_smoke_cc
    local logs=/tmp/mxtpu_autoscale_smoke_logs
    rm -rf "$cc" "$logs"
    JAX_PLATFORMS=cpu python tools/autoscale_smoke.py all \
        --cache-dir "$cc" --log-dir "$logs"
}

fleet_obs_smoke() {
    local cc=/tmp/mxtpu_fleet_obs_cc
    rm -rf "$cc"
    JAX_PLATFORMS=cpu python tools/fleet_obs_smoke.py all \
        --cache-dir "$cc" \
        --incident-dir /tmp/mxtpu_fleet_obs_incidents
}

device_obs_smoke() {
    local cc=/tmp/mxtpu_device_obs_cc
    rm -rf "$cc"
    JAX_PLATFORMS=cpu python tools/device_obs_smoke.py all \
        --cache-dir "$cc" \
        --profile-dir /tmp/mxtpu_device_obs_profiles
}

health_smoke() {
    local dir=/tmp/mxtpu_health_smoke
    rm -rf "$dir"
    mkdir -p "$dir/flight"
    JAX_PLATFORMS=cpu python tools/health_smoke.py golden --out "$dir"
    MXNET_HEALTH_PLANE=1 MXNET_FLIGHT_DUMP_DIR="$dir/flight" \
        JAX_PLATFORMS=cpu python tools/health_smoke.py poisoned \
        --out "$dir"
    JAX_PLATFORMS=cpu python tools/health_smoke.py check --out "$dir"
}

multichip_dryrun() {
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
}

[ $# -eq 1 ] || usage
declare -F "$1" >/dev/null || usage
"$1"
