#!/usr/bin/env bash
# Canonical "how to run everything" script (reference analog:
# ci/docker/runtime_functions.sh).  All suites run on a virtual
# 8-device CPU mesh unless a TPU tier is requested.
set -euo pipefail
cd "$(dirname "$0")/.."

usage() {
    cat <<EOF
usage: ci/run_tests.sh <function>
  unittest_cpu          full CPU suite (single run; ~30 min on 1 core)
  unittest_cpu_chunked  CPU suite in two halves (for constrained runners)
  unittest_tpu          TPU tier (tests_tpu/: op sweep on the live chip
                        + CPU-vs-TPU consistency; self-skips without one)
  smoke                 60-second end-to-end slice (gluon MNIST)
  telemetry_smoke       MNIST slice under MXNET_TELEMETRY=1; asserts the
                        Prometheus dump has nonzero op/step/compile counters
  bench                 judged benchmark (prints one JSON line; includes a
                        telemetry snapshot when MXNET_TELEMETRY=1)
  multichip_dryrun      8-virtual-device full-train-step compile+run
EOF
    exit 1
}

unittest_cpu() {
    python -m pytest tests/ -q
}

unittest_cpu_chunked() {
    mapfile -t files < <(ls tests/test_*.py | sort)
    half=$(( (${#files[@]} + 1) / 2 ))
    python -m pytest "${files[@]:0:half}" -q -p no:cacheprovider
    python -m pytest "${files[@]:half}" -q -p no:cacheprovider
}

unittest_tpu() {
    python -m pytest tests_tpu/ -q
}

smoke() {
    python example/gluon/mnist.py --cpu --epochs 1
}

telemetry_smoke() {
    local dump=/tmp/mxtpu_telemetry_smoke.prom
    rm -f "$dump"
    MXNET_TELEMETRY=1 MXNET_TELEMETRY_DUMP="$dump" \
        python example/gluon/mnist.py --cpu --epochs 1 --hybridize
    python - "$dump" <<'EOF'
import sys

vals = {}
for line in open(sys.argv[1]):
    line = line.strip()
    if not line or line.startswith("#"):
        continue
    name, _, val = line.rpartition(" ")
    base = name.split("{")[0]
    try:
        vals[base] = vals.get(base, 0.0) + float(val)
    except ValueError:
        pass

for metric in ("mx_op_dispatch_total", "mx_trainer_steps_total",
               "mx_compile_total", "mx_trainer_step_seconds_count"):
    assert vals.get(metric, 0) > 0, \
        f"telemetry_smoke: {metric} is zero/absent; got {sorted(vals)}"
print("telemetry_smoke ok:",
      {k: vals[k] for k in ("mx_op_dispatch_total",
                            "mx_trainer_steps_total", "mx_compile_total")})
EOF
}

bench() {
    python bench.py
}

multichip_dryrun() {
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
}

[ $# -eq 1 ] || usage
declare -F "$1" >/dev/null || usage
"$1"
