"""``mx.viz`` — network visualization utilities (reference:
python/mxnet/visualization.py: print_summary + plot_network).

``print_summary`` walks the Symbol DAG and tabulates per-layer output
shapes and parameter counts (shape inference runs through the symbol
layer's jax.eval_shape-backed inference).  ``plot_network`` renders via
graphviz when the package is present and raises a clear error otherwise
(zero-egress image: graphviz may be absent)."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as _np

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape: Optional[Dict] = None,
                  line_length: int = 120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a per-node summary table (reference: viz.print_summary).

    shape: dict of input name → shape, enabling output-shape and
    parameter counting via graph shape inference."""
    arg_shapes = {}
    out_shapes = {}
    if shape is not None:
        inferred_args, _, node_outs = _infer_all(symbol, shape)
        arg_shapes = inferred_args
        out_shapes = node_outs

    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    positions = [int(line_length * p) for p in positions]

    def print_row(cols):
        line = ""
        for i, col in enumerate(cols):
            line += str(col)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)

    total_params = 0
    for node in symbol._topo():
        if node.is_variable:
            continue
        params = 0
        for src, _ in node.inputs:
            if src.is_variable and src.name in arg_shapes \
                    and src.name not in (shape or {}):
                params += int(_np.prod(arg_shapes[src.name]))
        total_params += params
        prev = ",".join(src.name for src, _ in node.inputs
                        if not src.is_variable) or \
            ",".join(src.name for src, _ in node.inputs)
        oshape = out_shapes.get(node.name, "")
        print_row([f"{node.name} ({node.op})", oshape, params, prev])
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def _infer_all(symbol, shape):
    """(arg name → shape, out shapes, node name → output shape).

    ONE inference pass over get_internals() covers every node (the
    per-node-subgraph alternative re-evaluates each upstream subgraph —
    quadratic in depth)."""
    arg_sh, out_sh, _aux = symbol.infer_shape(**shape)
    args = dict(zip(symbol.list_arguments(), arg_sh))
    node_outs = {}
    internals = symbol.get_internals()
    try:
        _, int_outs, _ = internals.infer_shape(**shape)
        for name, s in zip(internals.list_outputs(), int_outs):
            base = name.rsplit("_output", 1)[0]
            node_outs.setdefault(base, s)
    except MXNetError:
        pass   # partial inference unavailable: leave shape cells blank
    return args, out_sh, node_outs


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz rendering of the Symbol DAG (reference: viz.plot_network).
    Requires the ``graphviz`` python package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network needs the graphviz package, which is not in "
            "this image; use print_summary for a text view") from e
    dot = Digraph(name=title, format=save_format)
    for node in symbol._topo():
        if node.is_variable:
            if hide_weights and node.name != "data" \
                    and ("weight" in node.name or "bias" in node.name
                         or "gamma" in node.name or "beta" in node.name):
                continue
            dot.node(node.name, node.name, shape="oval")
        else:
            dot.node(node.name, f"{node.name}\n{node.op}", shape="box")
        for src, _ in node.inputs:
            if hide_weights and src.is_variable and src.name != "data" \
                    and ("weight" in src.name or "bias" in src.name
                         or "gamma" in src.name or "beta" in src.name):
                continue
            dot.edge(src.name, node.name)
    return dot
