"""``gluon.contrib`` (reference: python/mxnet/gluon/contrib)."""
from . import nn
from . import estimator

__all__ = ["nn", "estimator"]
