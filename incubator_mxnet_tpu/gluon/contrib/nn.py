"""Contrib layers (reference: python/mxnet/gluon/contrib/nn/basic_layers.py
— Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle2D).

Concurrent/HybridConcurrent/Identity are the contrib-era names of what
later became nn.Concatenate/HybridConcatenate/Identity — aliased to the
single implementation in gluon.nn (the reference keeps both spellings
too)."""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import HybridBlock
from ..nn.basic_layers import (Embedding, Identity, Concatenate,
                               HybridConcatenate)
from ..nn import basic_layers as _bl

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle2D"]


class Concurrent(Concatenate):
    """Run children on the same input, concat outputs (reference:
    contrib.nn.Concurrent)."""


class HybridConcurrent(HybridConcatenate):
    """Hybridizable Concurrent (reference: contrib.nn.HybridConcurrent)."""


class SparseEmbedding(Embedding):
    """Embedding with row_sparse weight gradients (reference:
    contrib.nn.SparseEmbedding — for very large vocabularies only the
    touched rows carry gradient; here the sparse_grad=True Embedding
    provides exactly that, so this is the configured alias)."""

    def __init__(self, input_dim, output_dim, dtype=_np.float32,
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


class SyncBatchNorm(_bl.BatchNorm):
    """Cross-device synchronized BatchNorm (reference:
    contrib.nn.SyncBatchNorm, key=..., num_devices=...).

    SPMD note: under the compiled train step the batch statistics are
    computed over the GLOBAL (mesh-sharded) batch by construction — XLA's
    reduction over a sharded axis is already the cross-device sync the
    reference implements with an explicit allreduce — so this subclass
    only needs to accept the reference's extra arguments."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        kwargs.pop("key", None)
        super().__init__(in_channels=in_channels, momentum=momentum,
                         epsilon=epsilon, **kwargs)
        self._num_devices = num_devices


class PixelShuffle2D(HybridBlock):
    """Rearrange (N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2) (reference:
    contrib.nn.PixelShuffle2D — the sub-pixel upsampling layer, expressed
    with the reference's reshape special codes so it traces symbolically
    too)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor, factor)
        self._factor = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factor
        x = F.reshape(x, shape=(0, -4, -1, f1 * f2, 0, 0))  # N c f1f2 H W
        x = F.reshape(x, shape=(0, 0, -4, f1, f2, 0, 0))    # N c f1 f2 H W
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))         # N c H f1 W f2
        return F.reshape(x, shape=(0, 0, -3, -3))           # N c Hf1 Wf2
