"""``gluon.contrib.estimator`` — high-level fit/evaluate driver with event
handlers (reference: python/mxnet/gluon/contrib/estimator/estimator.py +
event_handler.py: Estimator, LoggingHandler, CheckpointHandler,
EarlyStoppingHandler, ValidationHandler)."""
from __future__ import annotations

import time
from typing import List, Optional

from ...base import MXNetError
from ... import metric as metric_mod
from .. import loss as loss_mod
from ..trainer import Trainer

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler", "ValidationHandler"]


# ---------------------------------------------------------------------------
# event mixin interfaces (reference: event_handler.py)
# ---------------------------------------------------------------------------
class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator):
        pass


class BatchEnd:
    def batch_end(self, estimator):
        pass


class StopTraining(Exception):
    pass


class Estimator:
    """Train/evaluate driver (reference: estimator.Estimator).

    net: a (Hybrid)Block; loss: a gluon loss Block; train_metrics: metric
    or list; trainer: a gluon Trainer (default: adam 1e-3).
    """

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        self.net = net
        if not isinstance(loss, loss_mod.Loss):
            raise MXNetError("loss must be a gluon loss")
        self.loss = loss
        if train_metrics is None:
            train_metrics = [metric_mod.create("acc")]
        if not isinstance(train_metrics, (list, tuple)):
            train_metrics = [train_metrics]
        self.train_metrics = [metric_mod.create(m) if isinstance(m, str)
                              else m for m in train_metrics]
        # separate instances: evaluate() must never clobber the train
        # metrics' running state
        import copy
        self.val_metrics_objs = [copy.deepcopy(m)
                                 for m in self.train_metrics]
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.context = context
        # state the handlers read
        self.current_epoch = 0
        self.processed_samples = 0
        self.train_loss = 0.0
        self.val_metrics = []
        self.stop_training = False
        # a resume-aware CheckpointHandler sets this in train_begin; fit()
        # then starts the epoch loop there instead of at 0
        self.resume_from_epoch = 0

    # ------------------------------------------------------------------
    def _batches(self, data):
        for batch in data:
            if isinstance(batch, (list, tuple)) and len(batch) >= 2:
                x, y = batch[0], batch[1]
            else:  # DataBatch from a DataIter
                x, y = batch.data[0], batch.label[0]
            if self.context is not None:
                x = x.as_in_context(self.context)
                y = y.as_in_context(self.context)
            yield x, y

    def evaluate(self, val_data, metrics=None):
        """Run metrics over a dataset (reference: Estimator.evaluate)."""
        from ... import autograd as _ag
        metrics = metrics or self.val_metrics_objs
        for m in metrics:
            m.reset()
        if hasattr(val_data, "reset"):
            val_data.reset()
        for x, y in self._batches(val_data):
            with _ag.predict_mode():
                out = self.net(x)
            for m in metrics:
                m.update([y], [out])
        return [(m.get()) for m in metrics]

    def fit(self, train_data, val_data=None, epochs=1,
            event_handlers: Optional[List] = None, batches=None):
        """Reference: Estimator.fit — epochs of forward/backward/step with
        handler callbacks at train/epoch/batch boundaries."""
        from ... import autograd as _ag
        handlers = list(event_handlers or [])
        handlers.append(_MetricUpdater())
        # validation must stamp fresh metrics BEFORE consumers (early
        # stopping, logging) read them (the reference orders handlers by
        # priority the same way)
        handlers.sort(key=lambda h: 0 if isinstance(h, ValidationHandler)
                      else 1)

        def fire(kind):
            for h in handlers:
                fn = getattr(h, kind, None)
                if fn is not None:
                    fn(self)

        # re-entrant fit: clear terminal state from a previous run
        self.stop_training = False
        self.val_metrics = []
        self.val_metrics_epoch = -1
        self.processed_samples = 0
        self.resume_from_epoch = 0
        fire("train_begin")   # a resuming CheckpointHandler restores here
        try:
            for epoch in range(self.resume_from_epoch, epochs):
                self.current_epoch = epoch
                for m in self.train_metrics:
                    m.reset()
                self.train_loss = 0.0
                nbatch = 0
                if hasattr(train_data, "reset"):
                    train_data.reset()
                fire("epoch_begin")
                for x, y in self._batches(train_data):
                    fire("batch_begin")
                    with _ag.record():
                        out = self.net(x)
                        # per-sample loss vector + step(batch_size) is the
                        # reference convention: backward sums, step divides
                        loss = self.loss(out, y)
                    loss.backward()
                    self.trainer.step(x.shape[0])
                    self.train_loss += float(loss.mean().asscalar())
                    self.processed_samples += x.shape[0]
                    self._last_batch = (y, out)
                    nbatch += 1
                    fire("batch_end")
                    if batches is not None and nbatch >= batches:
                        break
                self.train_loss /= max(nbatch, 1)
                if val_data is not None:
                    self.val_metrics = self.evaluate(val_data)
                    self.val_metrics_epoch = epoch
                fire("epoch_end")
                if self.stop_training:
                    break
        except StopTraining:
            pass
        fire("train_end")
        return self


class _MetricUpdater(BatchEnd):
    def batch_end(self, estimator):
        y, out = estimator._last_batch
        for m in estimator.train_metrics:
            m.update([y], [out])


# ---------------------------------------------------------------------------
# handlers (reference: event_handler.py)
# ---------------------------------------------------------------------------
class LoggingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Per-epoch logging (reference: LoggingHandler)."""

    def __init__(self, log_interval="epoch"):
        self.log_interval = log_interval
        self._t0 = None

    def train_begin(self, estimator):
        self._t0 = time.time()
        print(f"Training begin: {len(estimator.train_metrics)} metrics")

    def epoch_end(self, estimator):
        parts = [f"epoch {estimator.current_epoch}:",
                 f"loss {estimator.train_loss:.4f}"]
        for m in estimator.train_metrics:
            name, val = m.get()
            parts.append(f"train-{name} {val:.4f}")
        for name, val in estimator.val_metrics:
            parts.append(f"val-{name} {val:.4f}")
        print("  ".join(parts))

    def train_end(self, estimator):
        print(f"Training done in {time.time() - self._t0:.1f}s "
              f"({estimator.processed_samples} samples)")


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save every epoch (reference: CheckpointHandler; rides the async
    checkpointer).

    With ``save_states=True`` (default) each checkpoint is a FULL training
    snapshot — params + Trainer optimizer states + loss-scaler + RNG —
    published atomically.  With ``resume=True``, ``train_begin`` rehydrates
    net/trainer/scaler/RNG from the newest complete checkpoint and tells
    ``fit`` to continue from the following epoch, so a preempted run picks
    up where it stopped instead of restarting."""

    def __init__(self, model_dir, model_prefix="model", keep=3,
                 resume=False, save_states=True):
        from ...checkpoint import AsyncCheckpointer
        import os
        self._ckpt = AsyncCheckpointer(
            os.path.join(model_dir, model_prefix), keep=keep)
        self._resume = bool(resume)
        self._save_states = bool(save_states)

    def train_begin(self, estimator):
        if not self._resume:
            return
        scaler = getattr(estimator.trainer, "_amp_loss_scaler", None)
        step = self._ckpt.restore_into(
            params=estimator.net.collect_params(),
            trainer=estimator.trainer,
            scaler=scaler)
        if step is not None:
            # checkpoints are stamped with the epoch they finished —
            # resume at the next one
            estimator.resume_from_epoch = step + 1

    def epoch_end(self, estimator):
        params = {k: p.data() for k, p in
                  estimator.net.collect_params().items()}
        if self._save_states:
            self._ckpt.save(
                estimator.current_epoch, params,
                trainer=estimator.trainer,
                scaler=getattr(estimator.trainer, "_amp_loss_scaler", None),
                epoch=estimator.current_epoch)
        else:
            self._ckpt.save(estimator.current_epoch, params)

    def train_end(self, estimator):
        self._ckpt.wait_until_finished()


class EarlyStoppingHandler(EpochEnd):
    """Stop when a monitored metric stops improving (reference:
    EarlyStoppingHandler)."""

    def __init__(self, monitor_idx=0, mode="max", patience=3,
                 min_delta=0.0):
        self.monitor_idx = monitor_idx
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self._best = None
        self._bad = 0

    def epoch_end(self, estimator):
        if estimator.val_metrics:
            # only judge epochs with FRESH validation results — with a
            # coarser ValidationHandler cadence, stale metrics must not
            # count toward patience
            if getattr(estimator, "val_metrics_epoch",
                       estimator.current_epoch) != estimator.current_epoch:
                return
            source = estimator.val_metrics
        else:
            source = [m.get() for m in estimator.train_metrics]
        _, val = source[self.monitor_idx]
        improved = (self._best is None
                    or (self.mode == "max"
                        and val > self._best + self.min_delta)
                    or (self.mode == "min"
                        and val < self._best - self.min_delta))
        if improved:
            self._best = val
            self._bad = 0
        else:
            self._bad += 1
            if self._bad >= self.patience:
                estimator.stop_training = True


class ValidationHandler(EpochEnd):
    """Extra validation on a custom cadence (reference:
    ValidationHandler)."""

    def __init__(self, val_data, eval_fn=None, epoch_period=1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period

    def epoch_end(self, estimator):
        if estimator.current_epoch % self.epoch_period:
            return
        if self.eval_fn is not None:
            self.eval_fn(estimator, self.val_data)
        else:
            estimator.val_metrics = estimator.evaluate(self.val_data)
        estimator.val_metrics_epoch = estimator.current_epoch
