"""``gluon.contrib.estimator`` — high-level fit/evaluate driver with event
handlers (reference: python/mxnet/gluon/contrib/estimator/estimator.py +
event_handler.py: Estimator, LoggingHandler, CheckpointHandler,
EarlyStoppingHandler, ValidationHandler)."""
from __future__ import annotations

import time
from typing import List, Optional

from ...base import MXNetError, getenv_bool
from ... import metric as metric_mod
from .. import loss as loss_mod
from ..trainer import Trainer

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler", "ValidationHandler"]


# ---------------------------------------------------------------------------
# event mixin interfaces (reference: event_handler.py)
# ---------------------------------------------------------------------------
class TrainBegin:
    def train_begin(self, estimator):
        pass


class TrainEnd:
    def train_end(self, estimator):
        pass


class EpochBegin:
    def epoch_begin(self, estimator):
        pass


class EpochEnd:
    def epoch_end(self, estimator):
        pass


class BatchBegin:
    def batch_begin(self, estimator):
        pass


class BatchEnd:
    def batch_end(self, estimator):
        pass


class StopTraining(Exception):
    pass


class Estimator:
    """Train/evaluate driver (reference: estimator.Estimator).

    net: a (Hybrid)Block; loss: a gluon loss Block; train_metrics: metric
    or list; trainer: a gluon Trainer (default: adam 1e-3).
    """

    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        self.net = net
        if not isinstance(loss, loss_mod.Loss):
            raise MXNetError("loss must be a gluon loss")
        self.loss = loss
        if train_metrics is None:
            train_metrics = [metric_mod.create("acc")]
        if not isinstance(train_metrics, (list, tuple)):
            train_metrics = [train_metrics]
        self.train_metrics = [metric_mod.create(m) if isinstance(m, str)
                              else m for m in train_metrics]
        # separate instances: evaluate() must never clobber the train
        # metrics' running state
        import copy
        self.val_metrics_objs = [copy.deepcopy(m)
                                 for m in self.train_metrics]
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.context = context
        # state the handlers read
        self.current_epoch = 0
        self.processed_samples = 0
        self.train_loss = 0.0
        self.val_metrics = []
        self.stop_training = False
        # a resume-aware CheckpointHandler sets this in train_begin; fit()
        # then starts the epoch loop there instead of at 0
        self.resume_from_epoch = 0
        # set when fit() runs in compiled-loop mode (fit(compiled_loop=
        # True) or MXNET_COMPILED_LOOP); handlers that touch the trainer
        # (CheckpointHandler) retarget to it.  _loop_requested is stamped
        # by fit() BEFORE train_begin fires so a resuming handler knows
        # loop mode is coming even though the loop itself is built
        # lazily (a fresh process has compiled_loop=None at train_begin)
        self.compiled_loop = None
        self._loop_requested = False
        self._loop_steps_arg = None
        self._loop_mesh_arg = None
        self._last_batch = None

    # ------------------------------------------------------------------
    def _batches(self, data):
        for batch in data:
            if isinstance(batch, (list, tuple)) and len(batch) >= 2:
                x, y = batch[0], batch[1]
            else:  # DataBatch from a DataIter
                x, y = batch.data[0], batch.label[0]
            if self.context is not None:
                x = x.as_in_context(self.context)
                y = y.as_in_context(self.context)
            yield x, y

    def evaluate(self, val_data, metrics=None):
        """Run metrics over a dataset (reference: Estimator.evaluate)."""
        from ... import autograd as _ag
        metrics = metrics or self.val_metrics_objs
        for m in metrics:
            m.reset()
        if hasattr(val_data, "reset"):
            val_data.reset()
        for x, y in self._batches(val_data):
            with _ag.predict_mode():
                out = self.net(x)
            for m in metrics:
                m.update([y], [out])
        return [(m.get()) for m in metrics]

    def fit(self, train_data, val_data=None, epochs=1,
            event_handlers: Optional[List] = None, batches=None,
            compiled_loop=None, loop_steps=None, loop_mesh=None):
        """Reference: Estimator.fit — epochs of forward/backward/step with
        handler callbacks at train/epoch/batch boundaries.

        ``compiled_loop=True`` (or ``MXNET_COMPILED_LOOP=1``) trains each
        epoch through a :class:`parallel.CompiledLoop` instead of the
        eager per-batch path: k-step chunks dispatch as one donated
        program with device prefetch, the optimizer is the functional
        twin of this estimator's Trainer optimizer, and params sync back
        to the net at every epoch end (so validation, checkpointing and
        eager use keep working).  Per-batch handler events and train
        metrics are not fired in loop mode — there is no per-batch host
        boundary to fire them at; ``loop_steps`` sets the chunk length
        (default ``MXNET_LOOP_STEPS``).  The loop data-parallelizes over
        every visible device by default (the global batch must divide by
        ``jax.device_count()``); pass ``loop_mesh`` for a custom
        topology, e.g. ``make_mesh({"data": 1})`` for strict parity
        with the single-device eager Trainer."""
        from ... import autograd as _ag
        use_loop = bool(compiled_loop) if compiled_loop is not None \
            else getenv_bool("MXNET_COMPILED_LOOP", False)
        # stamped before train_begin: a resuming CheckpointHandler must
        # know loop mode is active while compiled_loop is still None
        self._loop_requested = use_loop
        self._loop_steps_arg = loop_steps
        self._loop_mesh_arg = loop_mesh
        handlers = list(event_handlers or [])
        handlers.append(_MetricUpdater())
        # validation must stamp fresh metrics BEFORE consumers (early
        # stopping, logging) read them (the reference orders handlers by
        # priority the same way)
        handlers.sort(key=lambda h: 0 if isinstance(h, ValidationHandler)
                      else 1)

        def fire(kind):
            for h in handlers:
                fn = getattr(h, kind, None)
                if fn is not None:
                    fn(self)

        # re-entrant fit: clear terminal state from a previous run
        self.stop_training = False
        self.val_metrics = []
        self.val_metrics_epoch = -1
        self.processed_samples = 0
        self.resume_from_epoch = 0
        fire("train_begin")   # a resuming CheckpointHandler restores here
        try:
            for epoch in range(self.resume_from_epoch, epochs):
                self.current_epoch = epoch
                for m in self.train_metrics:
                    m.reset()
                self.train_loss = 0.0
                nbatch = 0
                if hasattr(train_data, "reset"):
                    train_data.reset()
                fire("epoch_begin")
                if use_loop:
                    self._last_batch = None
                    nbatch = self._run_epoch_loop(train_data, batches)
                else:
                    for x, y in self._batches(train_data):
                        fire("batch_begin")
                        with _ag.record():
                            out = self.net(x)
                            # per-sample loss vector + step(batch_size)
                            # is the reference convention: backward sums,
                            # step divides
                            loss = self.loss(out, y)
                        loss.backward()
                        self.trainer.step(x.shape[0])
                        self.train_loss += float(loss.mean().asscalar())
                        self.processed_samples += x.shape[0]
                        self._last_batch = (y, out)
                        nbatch += 1
                        fire("batch_end")
                        if batches is not None and nbatch >= batches:
                            break
                self.train_loss /= max(nbatch, 1)
                if val_data is not None:
                    self.val_metrics = self.evaluate(val_data)
                    self.val_metrics_epoch = epoch
                fire("epoch_end")
                if self.stop_training:
                    break
        except StopTraining:
            pass
        fire("train_end")
        return self

    # ------------------------------------------------------------------
    # compiled-loop mode (parallel.CompiledLoop; docs/performance.md)
    def _build_compiled_loop(self):
        import jax
        from ...optimizer.fused import functional_twin
        from ...parallel import CompiledLoop, make_mesh
        mesh = self._loop_mesh_arg
        if mesh is None:
            # data-parallel over every visible device, like SPMDTrainer's
            # documented default usage; the global batch must divide by
            # the device count (fit(loop_mesh=make_mesh({"data": 1}))
            # forces the single-device layout)
            mesh = make_mesh({"data": jax.device_count()})
        twin = functional_twin(self.trainer._optimizer)
        # the Trainer already folded the MXNET_ZERO1 env default into its
        # request flag — propagate it so eager and loop mode agree on the
        # sharding tier; a non-elementwise rule (LAMB) silently degrades
        # to the unsharded loop, mirroring the Trainer's fused fallback
        z1 = bool(getattr(self.trainer, "_zero1_requested", False))
        if z1 and not getattr(twin, "elementwise", True):
            z1 = False
        self.compiled_loop = CompiledLoop(
            self.net, self.loss, twin,
            loop_steps=self._loop_steps_arg,
            skip_nonfinite=bool(getattr(self.trainer, "_skip_nonfinite",
                                        False)),
            zero1=z1,
            mesh=mesh)
        return self.compiled_loop

    def _run_epoch_loop(self, train_data, batches):
        from ... import autograd as _ag
        gen = self._batches(train_data)
        first = next(gen, None)
        if first is None:
            return 0
        if self.compiled_loop is None:
            try:
                self._build_compiled_loop()
            except MXNetError:
                # deferred shapes: settle with one paused forward, then
                # build for real (any other config error re-raises below)
                with _ag.pause():
                    self.net(first[0])
                self._build_compiled_loop()
        loop = self.compiled_loop
        sizes = []

        def stream():
            x, y = first
            while True:
                sizes.append(int(x.shape[0]))
                yield (x, y)
                nxt = next(gen, None)
                if nxt is None:
                    return
                x, y = nxt

        losses = loop.run(stream(), steps=batches)
        n = int(losses.shape[0])
        self.processed_samples += sum(sizes[:n])
        # sum of per-step mean losses: fit() divides by nbatch, matching
        # the eager path's mean-of-batch-means
        self.train_loss = float(losses.sum())
        loop.sync_to_block()
        return n


class _MetricUpdater(BatchEnd):
    def batch_end(self, estimator):
        if getattr(estimator, "_last_batch", None) is None:
            return    # compiled-loop mode: no per-batch host boundary
        y, out = estimator._last_batch
        for m in estimator.train_metrics:
            m.update([y], [out])


# ---------------------------------------------------------------------------
# handlers (reference: event_handler.py)
# ---------------------------------------------------------------------------
class LoggingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Per-epoch logging (reference: LoggingHandler)."""

    def __init__(self, log_interval="epoch"):
        self.log_interval = log_interval
        self._t0 = None

    def train_begin(self, estimator):
        self._t0 = time.time()
        print(f"Training begin: {len(estimator.train_metrics)} metrics")

    def epoch_end(self, estimator):
        parts = [f"epoch {estimator.current_epoch}:",
                 f"loss {estimator.train_loss:.4f}"]
        for m in estimator.train_metrics:
            name, val = m.get()
            parts.append(f"train-{name} {val:.4f}")
        for name, val in estimator.val_metrics:
            parts.append(f"val-{name} {val:.4f}")
        print("  ".join(parts))

    def train_end(self, estimator):
        print(f"Training done in {time.time() - self._t0:.1f}s "
              f"({estimator.processed_samples} samples)")


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save every epoch (reference: CheckpointHandler; rides the async
    checkpointer).

    With ``save_states=True`` (default) each checkpoint is a FULL training
    snapshot — params + Trainer optimizer states + loss-scaler + RNG —
    published atomically.  With ``resume=True``, ``train_begin`` rehydrates
    net/trainer/scaler/RNG from the newest complete checkpoint and tells
    ``fit`` to continue from the following epoch, so a preempted run picks
    up where it stopped instead of restarting."""

    def __init__(self, model_dir, model_prefix="model", keep=3,
                 resume=False, save_states=True):
        from ...checkpoint import AsyncCheckpointer
        import os
        self._ckpt = AsyncCheckpointer(
            os.path.join(model_dir, model_prefix), keep=keep)
        self._resume = bool(resume)
        self._save_states = bool(save_states)

    def train_begin(self, estimator):
        if not self._resume:
            return
        scaler = getattr(estimator.trainer, "_amp_loss_scaler", None)
        # in compiled-loop mode the loop owns optimizer state + step
        # counter; its states were what epoch_end saved
        loop = getattr(estimator, "compiled_loop", None)
        if loop is None and getattr(estimator, "_loop_requested", False):
            # fresh-process resume in loop mode: the loop is built
            # lazily and does not exist yet, and routing its checkpoint
            # blob into the eager Trainer would install foreign updater
            # state (fresh optimizer state + step 0 under an advanced
            # epoch counter).  Restore params FIRST — that also settles
            # deferred shapes from the saved arrays — then build the
            # loop from the restored net and hand it its own states.
            step = self._ckpt.restore_into(
                params=estimator.net.collect_params(), scaler=scaler)
            if step is None:
                return          # no checkpoint yet: start fresh
            loop = estimator._build_compiled_loop()
            self._ckpt.restore_into(trainer=loop, step=step)
            estimator.resume_from_epoch = step + 1
            return
        step = self._ckpt.restore_into(
            params=estimator.net.collect_params(),
            trainer=loop or estimator.trainer,
            scaler=scaler)
        if step is not None:
            # checkpoints are stamped with the epoch they finished —
            # resume at the next one
            estimator.resume_from_epoch = step + 1
            if loop is not None:
                loop.reload_params()

    def epoch_end(self, estimator):
        loop = getattr(estimator, "compiled_loop", None)
        # the full collect_params() snapshot is correct in BOTH modes:
        # in loop mode _run_epoch_loop's sync_to_block already mirrored
        # the loop's current values — including aux state like BatchNorm
        # running stats, which loop.params (trainable only) would drop —
        # into the net; the loop's states carry the in-scan step counter
        # + optimizer state so resume is exact
        params = {k: p.data() for k, p in
                  estimator.net.collect_params().items()}
        target = loop if loop is not None else estimator.trainer
        if self._save_states:
            self._ckpt.save(
                estimator.current_epoch, params,
                trainer=target,
                scaler=getattr(estimator.trainer, "_amp_loss_scaler", None),
                epoch=estimator.current_epoch)
        else:
            self._ckpt.save(estimator.current_epoch, params)

    def train_end(self, estimator):
        self._ckpt.wait_until_finished()


class EarlyStoppingHandler(EpochEnd):
    """Stop when a monitored metric stops improving (reference:
    EarlyStoppingHandler)."""

    def __init__(self, monitor_idx=0, mode="max", patience=3,
                 min_delta=0.0):
        self.monitor_idx = monitor_idx
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self._best = None
        self._bad = 0

    def epoch_end(self, estimator):
        if estimator.val_metrics:
            # only judge epochs with FRESH validation results — with a
            # coarser ValidationHandler cadence, stale metrics must not
            # count toward patience
            if getattr(estimator, "val_metrics_epoch",
                       estimator.current_epoch) != estimator.current_epoch:
                return
            source = estimator.val_metrics
        else:
            source = [m.get() for m in estimator.train_metrics]
        _, val = source[self.monitor_idx]
        improved = (self._best is None
                    or (self.mode == "max"
                        and val > self._best + self.min_delta)
                    or (self.mode == "min"
                        and val < self._best - self.min_delta))
        if improved:
            self._best = val
            self._bad = 0
        else:
            self._bad += 1
            if self._bad >= self.patience:
                estimator.stop_training = True


class ValidationHandler(EpochEnd):
    """Extra validation on a custom cadence (reference:
    ValidationHandler)."""

    def __init__(self, val_data, eval_fn=None, epoch_period=1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period

    def epoch_end(self, estimator):
        if estimator.current_epoch % self.epoch_period:
            return
        if self.eval_fn is not None:
            self.eval_fn(estimator, self.val_data)
        else:
            estimator.val_metrics = estimator.evaluate(self.val_data)
        estimator.val_metrics_epoch = estimator.current_epoch
