"""Gluon Trainer (reference: python/mxnet/gluon/trainer.py).

Applies an Optimizer to a set of Parameters.  step(batch_size) =
grad rescale → (kvstore aggregation if distributed) → optimizer update.
With one logical sharded array per Parameter there is no per-device grad
list to reduce — cross-device aggregation happens inside the compiled step
(parallel package); the KVStore path is kept for API parity and for the
update_on_kvstore contract.
"""
from __future__ import annotations

import time as _time
from typing import Optional

from ..base import MXNetError, getenv_bool
from .. import optimizer as opt_mod
from .. import telemetry as _telemetry
from .. import fault as _fault
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, skip_nonfinite=None,
                 fused=None, zero1=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "params must be a ParameterDict / list of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._params.append(p)
            self._param2idx[p.name] = i
        self._compression_params = compression_params
        # 'none' is accepted-but-inert; only 2bit changes push semantics
        self._compress_active = bool(
            compression_params
            and compression_params.get("type") == "2bit")
        self._contains_sparse = any(p.stype != "default"
                                    for p in self._params)
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states_to_load = None
        # opt-in non-finite grad guard (graceful degradation: skip the
        # update instead of corrupting params); costs one fused device
        # sync per step, so it stays off unless asked for
        self._skip_nonfinite = getenv_bool("MXNET_SKIP_NONFINITE", False) \
            if skip_nonfinite is None else bool(skip_nonfinite)
        # fused whole-tree update: one donated jit dispatch per step
        # instead of one dispatch per parameter (optimizer/fused.py);
        # falls back to the per-param loop automatically for sparse
        # params, update_on_kvstore, dist stores, and optimizers the
        # fused envelope does not cover
        self._fused_requested = getenv_bool("MXNET_FUSED_OPTIMIZER", True) \
            if fused is None else bool(fused)
        # ZeRO-1 weight-update sharding (arXiv:2004.13336): the fused
        # dispatch shards the flat update + optimizer state across the
        # local devices and all-gathers the weights back, all inside the
        # one donated jit call.  Implies the fused path; falls back with
        # it (and to replicated fused for non-elementwise rules).
        self._zero1_requested = getenv_bool("MXNET_ZERO1", False) \
            if zero1 is None else bool(zero1)
        if self._zero1_requested and fused is None:
            self._fused_requested = True
        self._fused = None
        # True once the fused path was tried for the optimizer
        # application in flight — _update must not re-run the host-side
        # setup (and bookkeeping) when step() already attempted it
        self._fused_attempted = False
        self._updatable = None
        # device-side all-finite flags from fused guarded steps awaiting
        # async readback (skipped-step accounting without a host sync)
        self._pending_nonfinite = []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer,
                                             param_dict=param_dict,
                                             **optimizer_params)
        self._updaters = opt_mod.get_updater(self._optimizer)

    def _init_kvstore(self):
        from .. import kvstore as kv_mod
        # the updatable-param list is static for the life of the Trainer
        # — precompute it once instead of re-checking grad_req and
        # re-calling p.grad()/p.data() accessors on every step
        self._updatable = [(i, p) for i, p in enumerate(self._params)
                           if p.grad_req != "null"]
        if self._kvstore_type is None:
            self._kvstore = None
        elif isinstance(self._kvstore_type, str):
            self._kvstore = kv_mod.create(self._kvstore_type)
        else:
            self._kvstore = self._kvstore_type
        # dist/tpu stores aggregate gradients (across mesh devices and
        # processes) even when the optimizer runs locally — the reference's
        # update_on_kvstore=False flow (push grad, pull aggregated grad,
        # update locally; trainer.py _allreduce_grads)
        self._distributed = (self._kvstore is not None and getattr(
            self._kvstore, "_is_dist", lambda: False)())
        if self._kvstore is not None and self._compression_params:
            # validate eagerly so a non-dist store raises instead of
            # silently dropping the compression config
            self._kvstore.set_gradient_compression(self._compression_params)
        if self._kvstore is not None and (self._update_on_kvstore
                                          or self._distributed):
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            for i, p in self._updatable:
                self._kvstore.init(i, p.data())
        self._fused = None
        sparse_grads = any(
            getattr(p, "_grad_stype", "default") != "default"
            for _, p in self._updatable)
        multi_worker = (self._distributed
                        and getattr(self._kvstore, "num_workers", 1) > 1)
        # zero1 lifts the multi-worker exclusion: with the gradient
        # aggregation reduce-scatter-shaped (each replica owns its
        # shard's reduction — _allreduce_grads below), the fused single
        # dispatch and a distributed kvstore compose instead of being
        # mutually exclusive tiers
        if (self._fused_requested and not self._contains_sparse
                and not sparse_grads
                and not self._update_on_kvstore
                and (not multi_worker or self._zero1_requested)):
            from ..optimizer.fused import FusedUpdater
            self._fused = FusedUpdater(self._updaters,
                                       zero1=self._zero1_requested)
        self._kv_initialized = True
        if self._states_to_load is not None:
            self.load_states(self._states_to_load)
            self._states_to_load = None

    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """One optimization step; grads are rescaled by 1/batch_size
        (reference: Trainer.step).  Timing is dispatch time: the update
        itself is async, so blocking waits show up in the op/sync planes,
        not here.

        With ``skip_nonfinite`` on (ctor arg or ``MXNET_SKIP_NONFINITE``),
        a step whose gradients contain NaN/Inf is SKIPPED — grads are
        zeroed, ``mxtpu_skipped_steps`` is bumped, and params stay
        untouched — instead of poisoning the weights and every step
        after.  On the fused path the all-finite check and the gating
        run INSIDE the one compiled dispatch (no host sync); skipped
        steps are counted on async readback, so the counter can trail
        by the in-flight steps until :meth:`sync_nonfinite_guard`."""
        observe = bool(_telemetry.TRAINER.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        with _telemetry.trace_span("trainer.step", cat="trainer",
                                   batch_size=batch_size):
            if not self._kv_initialized:
                self._init_kvstore()
            self._drain_nonfinite(block=False)
            self._optimizer.rescale_grad = self._scale / batch_size
            self._allreduce_grads()
            if _fault.take("trainer.grad", "nonfinite"):
                self._poison_grads()
            fused_done = False
            self._fused_attempted = False
            # an instance-level _update (e.g. amp.init_trainer's overflow
            # wrapper) must stay in the path: route through it and let the
            # fused call inside the class _update take over afterwards
            if self._fused is not None and "_update" not in self.__dict__:
                self._fused_attempted = True
                with _telemetry.trace_span("trainer.update", cat="trainer"):
                    fused_done, flag = self._fused.step(
                        self._updatable, guard=self._skip_nonfinite)
                if fused_done and flag is not None:
                    self._pending_nonfinite.append(flag)
            if not fused_done:
                if self._skip_nonfinite and self._grads_nonfinite():
                    _telemetry.FAULT.publish(site="trainer.step",
                                             event="skipped_step")
                    for _, p in self._updatable:
                        p.zero_grad()
                else:
                    with _telemetry.trace_span("trainer.update",
                                               cat="trainer"):
                        self._update(ignore_stale_grad)
        if observe:
            _telemetry.TRAINER.publish(
                phase="step", seconds=_time.perf_counter() - t0)

    def _drain_nonfinite(self, block=False):
        """Account skipped steps from fused guarded dispatches.  Without
        ``block`` only flags whose computation already finished are
        consumed (``is_ready`` — no host sync on the hot path)."""
        if not self._pending_nonfinite:
            return
        keep = []
        for flag in self._pending_nonfinite:
            if not block and not flag.is_ready():
                keep.append(flag)
                continue
            if not bool(flag):
                _telemetry.FAULT.publish(site="trainer.step",
                                         event="skipped_step")
        self._pending_nonfinite = keep

    def sync_nonfinite_guard(self):
        """Block until every in-flight fused ``skip_nonfinite`` flag is
        known, so ``mxtpu_skipped_steps`` is exact.  Call before reading
        the counter (monitors do; the training loop never needs to)."""
        self._drain_nonfinite(block=True)

    def sync_health(self):
        """Block until pending health-plane device stats are folded into
        the StepHealth ring (health.py) — exact records/anomalies for a
        monitor about to read them.  No-op with ``MXNET_HEALTH_PLANE``
        off or when the fused path never engaged."""
        if self._fused is not None and self._fused._health is not None:
            self._fused._health.sync()

    def _grads_nonfinite(self) -> bool:
        # one fused check, one host sync (amp.all_finite)
        from ..contrib.amp.loss_scaler import all_finite
        grads = [p.grad() for _, p in self._updatable
                 if p.grad() is not None]
        return not all_finite(grads)

    def _poison_grads(self):
        """Inject a non-finite gradient (fault site ``trainer.grad``) —
        the deterministic test hook behind the skip guard."""
        import jax.numpy as jnp
        for _, p in self._updatable:
            if p.grad() is not None:
                g = p.grad()
                g._set_data(jnp.full_like(g._data, jnp.nan))
                break

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        # one logical grad per param — single-process cross-device
        # reduction is inside the compiled step (psum).  For dist/tpu
        # stores the gradient is pushed (summed across processes over DCN)
        # and the aggregate pulled back before the local update
        if self._kvstore is None:
            return
        if self._update_on_kvstore:
            for i, p in self._updatable:
                self._kvstore.push(i, p.grad())
                self._kvstore.pull(i, p.data())
        elif self._distributed and (self._kvstore.num_workers > 1
                                    or self._compress_active):
            if self._zero1_requested and self._fused is not None \
                    and not self._compress_active:
                # zero1: allreduce decomposed as reduce-scatter (this
                # worker owns the reduction of its contiguous slice) +
                # all-gather — the arXiv:2004.13336 shape, same fault /
                # retry sites as push/pull
                for i, p in self._updatable:
                    self._kvstore.pushpull_rs(i, p.grad(), out=p.grad())
            else:
                # single process without compression: the DCN sum is the
                # identity — skip the two full-parameter copies per step
                for i, p in self._updatable:
                    self._kvstore.pushpull(i, p.grad(), out=p.grad())

    def update(self, batch_size, ignore_stale_grad=False):
        observe = bool(_telemetry.TRAINER.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        with _telemetry.trace_span("trainer.update", cat="trainer"):
            if not self._kv_initialized:
                self._init_kvstore()
            self._optimizer.rescale_grad = self._scale / batch_size
            self._fused_attempted = False
            self._update(ignore_stale_grad)
        if observe:
            _telemetry.TRAINER.publish(
                phase="update", seconds=_time.perf_counter() - t0)

    def _update(self, ignore_stale_grad=False):
        if self._kvstore is not None and self._update_on_kvstore:
            return  # server applied it in _allreduce_grads
        if self._fused is not None and not self._fused_attempted:
            self._fused_attempted = True
            if self._fused.step(self._updatable, guard=False)[0]:
                return
        for i, p in self._updatable:
            self._updaters(i, p.grad(), p.data())
        if _telemetry.enabled():
            _telemetry.gauge("mxtpu_optimizer_dispatches_per_step").set(
                len(self._updatable))

    # ------------------------------------------------------------------
    def get_states(self) -> bytes:
        """Serialized updater states incl. the optimizer (the in-memory
        twin of save_states — the checkpointer snapshots these on the
        caller thread so the async write sees a frozen picture)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            return self._kvstore._updater.get_states(dump_optimizer=True)
        if self._fused is not None:
            # zero1 keeps state as flat shards — materialize into the
            # per-param dict so the blob stays format-compatible
            self._fused.flush_states()
        return self._updaters.get_states(dump_optimizer=True)

    def set_states(self, states: bytes):
        """Restore updater states serialized by :meth:`get_states`."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore._updater.set_states(states)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            if self._fused is not None:
                # the restored per-param dict is the truth now
                self._fused.invalidate()
            self._updaters.set_states(states)
            self._optimizer = self._updaters.optimizer

    def save_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            if self._fused is not None:
                self._fused.flush_states()
            with open(fname, "wb") as f:
                f.write(self._updaters.get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._optimizer
        else:
            if self._fused is not None:
                self._fused.invalidate()
            with open(fname, "rb") as f:
                self._updaters.set_states(f.read())
            self._optimizer = self._updaters.optimizer
