"""Gluon Parameter / ParameterDict (reference: python/mxnet/gluon/parameter.py).

TPU-native re-design: the reference keeps one NDArray copy per device
(``list_data``) and aggregates gradients via KVStore; here a Parameter owns a
SINGLE logical NDArray — multi-device placement is a *sharding* of that one
array over a mesh (jax.sharding), not replication, so ``list_data`` returns
the one logical array per requested ctx.  Deferred shape inference
(``shape=(0,...)`` until first forward) matches the reference.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context, cpu
from .. import initializer as init_mod
from ..ndarray import ndarray as _ndmod
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its deferred shape was known
    (reference: gluon/parameter.py same name)."""


class Parameter:
    """A weight/bias/state tensor of a Block.

    grad_req: 'write' | 'add' | 'null'.  A shape containing 0 defers
    allocation until the first forward infers the full shape (reference:
    Parameter._finish_deferred_init).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._data: Optional[NDArray] = None
        self._ctx_list = None
        self._deferred_init = None   # (initializer, ctx, default_init)
        if stype not in ("default", "row_sparse", "csr"):
            raise MXNetError(f"invalid stype {stype!r}")
        if grad_stype not in ("default", "row_sparse", "csr"):
            raise MXNetError(f"invalid grad_stype {grad_stype!r}")
        self._stype = stype
        self._grad_stype = grad_stype

    # ------------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        # merge: 0 means unknown (reference shape merging semantics)
        if len(self._shape) != len(new_shape) or any(
                s != 0 and s != n for s, n in zip(self._shape, new_shape)):
            raise MXNetError(
                f"inconsistent shape for Parameter {self.name}: "
                f"{self._shape} vs {new_shape}")
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._require_grad = False
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._data.attach_grad(req, stype=self._grad_stype)

    @property
    def stype(self):
        return self._stype

    def _shape_known(self) -> bool:
        return self._shape is not None and all(s > 0 for s in self._shape)

    # ------------------------------------------------------------------
    # initialization
    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Allocate & fill data (reference: Parameter.initialize)."""
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = init_mod.Uniform()
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name}: shape "
                f"{self._shape} unknown; set allow_deferred_init=True "
                "or provide in_units/in_channels")
        self._init_impl(init, default_init)

    def _init_impl(self, init, default_init):
        ctx0 = self._ctx_list[0] if self._ctx_list else current_context()
        initializer = init_mod.create(
            init if init is not None else
            (self.init if self.init is not None else default_init))
        arr = _ndmod.zeros(self._shape, ctx=ctx0, dtype=self.dtype)
        initializer(init_mod.InitDesc(self.name), arr)
        self._data = arr
        self._deferred_init = None
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req, stype=self._grad_stype)

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not self._shape_known():
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape}")
        init, ctx, default_init = self._deferred_init
        self._ctx_list = ctx
        self._init_impl(init, default_init)

    # ------------------------------------------------------------------
    # access
    # ------------------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"Parameter {self.name} was not initialized yet: its shape "
                "is deferred to the first forward. Run a forward pass first")
        raise MXNetError(
            f"Parameter {self.name} has not been initialized. "
            "Call .initialize() on the Block first")

    def data(self, ctx=None) -> NDArray:
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None) -> NDArray:
        self._check_initialized()
        if self._grad_req == "null":
            raise MXNetError(
                f"Parameter {self.name} has grad_req='null': no gradient")
        return self._data.grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return self._deferred_init[1]
        self._check_initialized()
        return self._ctx_list or [self._data.ctx]

    def set_data(self, data):
        """Overwrite the value keeping grad buffer (reference: set_data)."""
        if isinstance(data, NDArray):
            src = data._data
        else:
            src = _np.asarray(data)
        if self._data is None:
            if self._shape_known() or self._deferred_init is None:
                self.shape = tuple(src.shape)
                self._ctx_list = self._ctx_list or [current_context()]
                arr = _ndmod.array(_np.asarray(src), ctx=self._ctx_list[0],
                                   dtype=self.dtype)
                self._data = arr
                if self._grad_req != "null":
                    self._data.attach_grad(self._grad_req, stype=self._grad_stype)
                self._deferred_init = None
                return
            self._check_initialized()
        import jax.numpy as jnp
        self._data._set_data(jnp.asarray(src, dtype=self._data.dtype))

    def zero_grad(self):
        if self._data is not None and self._data.grad is not None:
            self._data.zero_grad()

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req, stype=self._grad_stype)

    def cast(self, dtype):
        self.dtype = _np.dtype(dtype)
        if self._data is not None:
            had_grad = self._data.grad is not None
            self._data = self._data.astype(dtype)
            if had_grad:
                self._data.attach_grad(self._grad_req, stype=self._grad_stype)

    def var(self):
        from ..symbol import var as _svar
        return _svar(self.name, shape=self.shape, dtype=self.dtype,
                     lr_mult=self.lr_mult, wd_mult=self.wd_mult)

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self._shape}, "
                f"dtype={_np.dtype(self.dtype).name})")


class Constant(Parameter):
    """Non-trainable constant parameter (reference: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, _np.ndarray):
            value = _np.asarray(
                value.asnumpy() if isinstance(value, NDArray) else value)
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=init_mod.Constant(0.0), differentiable=False)

    def _init_impl(self, init, default_init):
        ctx0 = self._ctx_list[0] if self._ctx_list else current_context()
        self._data = _ndmod.array(self.value, ctx=ctx0, dtype=self.dtype)
        self._deferred_init = None


class ParameterDict:
    """Ordered name→Parameter mapping with a shared prefix
    (reference: gluon/parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def get(self, name, **kwargs) -> Parameter:
        """Create-or-retrieve prefix+name (reference: ParameterDict.get)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = v
                elif k == "init" and v is not None:
                    param.init = v
                elif hasattr(param, k) and v is not None:
                    pass  # keep the first definition (shared param case)
        return param

    def get_constant(self, name, value=None) -> Constant:
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant named {full}")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate Parameter {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import utils as nd_utils
        arg_dict = {}
        for name, p in self.items():
            weight = p.data()
            if not name.startswith(strip_prefix):
                raise MXNetError(
                    f"Parameter {name} does not start with prefix "
                    f"{strip_prefix}")
            arg_dict[name[len(strip_prefix):]] = weight
        nd_utils.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import utils as nd_utils
        loaded = nd_utils.load(filename)
        arg_dict = {restore_prefix + k.split(":", 1)[-1]: v
                    for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError(
                        f"Parameter {name} missing in file {filename}")
        for name, v in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(
                        f"Parameter {name} in file {filename} is not in "
                        "this ParameterDict")
                continue
            self._params[name].set_data(v)

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self.values())
        return f"ParameterDict(prefix={self._prefix!r}\n{s}\n)"
