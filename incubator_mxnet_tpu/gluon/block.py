"""Gluon Block / HybridBlock (reference: python/mxnet/gluon/block.py).

TPU-native re-design of the define-by-run module system:

* ``Block`` — pure imperative container, same registration/naming/param
  collection semantics as the reference.
* ``HybridBlock.hybridize()`` — the reference traces one forward into an
  nnvm graph executed by CachedOp (reference: src/imperative/cached_op.cc).
  Here ``hybridize`` traces the SAME eager code under ``jax.jit``: one
  compiled XLA program per (input shapes/dtypes, train-mode) key.  The whole
  forward becomes a single fused program — strictly stronger than the
  reference's op-bulking.  Autograd sees the jitted call as one tape node
  whose VJP is jax's VJP of the compiled function.
* BatchNorm-style running statistics are functional under the trace: layers
  route updates through ``update_aux``, which a trace collector turns into
  extra outputs of the compiled program, written back after each call
  (the reference mutates aux NDArrays inside the op instead).
* RNG under the trace flows through ``mx.random.trace_stream`` so dropout
  gets a fresh, traced key argument per call.
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from .. import autograd as _ag
from .. import random as _random
from .. import telemetry as _telemetry
from ..ndarray import ndarray as _ndmod
from ..ndarray.ndarray import NDArray, _invoke
from .parameter import (Parameter, ParameterDict,
                        DeferredInitializationError)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "update_aux",
           "functional_call"]

_naming = threading.local()
_trace = threading.local()


def _counters():
    if not hasattr(_naming, "counters"):
        _naming.counters = [{}]   # stack of per-scope counters
        _naming.prefixes = [""]
    return _naming


def _gen_prefix(hint: str) -> str:
    st = _counters()
    cnt = st.counters[-1]
    i = cnt.get(hint, 0)
    cnt[hint] = i + 1
    return f"{st.prefixes[-1]}{hint}{i}_"


class _NameScope:
    """Prefix scope entered during child construction (reference:
    block.py _BlockScope)."""

    def __init__(self, prefix):
        self._prefix = prefix

    def __enter__(self):
        st = _counters()
        st.prefixes.append(self._prefix)
        st.counters.append({})
        return self

    def __exit__(self, *exc):
        st = _counters()
        st.prefixes.pop()
        st.counters.pop()
        return False


def update_aux(param: Parameter, new_value):
    """Write a new value into an auxiliary (non-differentiable) parameter —
    running stats etc.  Eagerly sets the data; under a hybridize trace the
    value is collected and becomes an output of the compiled program."""
    coll = getattr(_trace, "collector", None)
    jval = new_value._data if isinstance(new_value, NDArray) else new_value
    if coll is not None:
        coll[id(param)] = jval
    else:
        param._data._set_data(jval.astype(param._data.dtype))


class Block:
    """Base container (reference: gluon.Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix = (prefix if prefix is not None
                        else _gen_prefix(self._alias()))
        self._params = ParameterDict(self._prefix, shared=params)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._forward_hooks = []
        self._forward_pre_hooks = []
        self._scope = _NameScope(self._prefix)

    def _alias(self):
        return self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    def name_scope(self):
        """Context manager giving children this block's name prefix."""
        return self._scope

    @property
    def params(self) -> ParameterDict:
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        """All params of self + descendants, optionally regex-filtered
        (reference: Block.collect_params)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            ret.update({k: v for k, v in self._params.items()
                        if pat.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
                self.__dict__.get("_params", ParameterDict())._params[
                    value.name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)
        return hook

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return hook

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks except propagation (reference behavior)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._params.values():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    # ------------------------------------------------------------------
    # serialization (reference: save_parameters uses structural names from
    # _collect_params_with_prefix, e.g. "features.0.weight")
    # ------------------------------------------------------------------
    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + n: p for n, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename):
        from ..ndarray import utils as nd_utils
        params = self._collect_params_with_prefix()
        nd_utils.save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        from ..ndarray import utils as nd_utils
        loaded = nd_utils.load(filename)
        if not isinstance(loaded, dict):
            raise MXNetError(f"{filename} does not contain a parameter dict")
        # strip legacy "arg:"/"aux:" prefixes (reference checkpoint compat)
        loaded = {k.split(":", 1)[-1] if k.startswith(("arg:", "aux:"))
                  else k: v for k, v in loaded.items()}
        params = self._collect_params_with_prefix()
        if not any(k in params for k in loaded) and loaded:
            # fall back to full-name (prefixed) matching
            byname = {p.name: p for p in self.collect_params().values()}
            params = byname
        for name, p in params.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(
                        f"Parameter {name} missing in {filename}")
                continue
            p.set_data(loaded[name])
        if not ignore_extra:
            for k in loaded:
                if k not in params:
                    raise MXNetError(
                        f"Parameter {k} from {filename} not found in Block")

    save_params = save_parameters      # deprecated aliases kept for parity
    load_params = load_parameters

    # ------------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference: Block.summary)."""
        rows = []

        def walk(block, indent=0):
            pcount = sum(int(_np.prod(p.shape)) if p.shape else 0
                         for p in block._reg_params.values())
            rows.append((("  " * indent) + block.__class__.__name__,
                         block.name, pcount))
            for c in block._children.values():
                walk(c, indent + 1)
        walk(self)
        total = sum(r[2] for r in rows)
        lines = [f"{'Layer':<40}{'Name':<28}{'Params':>12}", "-" * 80]
        lines += [f"{r[0]:<40}{r[1]:<28}{r[2]:>12}" for r in rows]
        lines += ["-" * 80, f"{'Total params':<68}{total:>12}"]
        print("\n".join(lines))

    def __repr__(self):
        s = f"{self.__class__.__name__}("
        for name, child in self._children.items():
            c = repr(child).replace("\n", "\n  ")
            s += f"\n  ({name}): {c}"
        return s + ("\n)" if self._children else ")")


def functional_call(block, params, param_vals, aux_params, aux_vals,
                    inputs_nd, training, rng_key):
    """Run ``block``'s forward as a PURE function of parameter values.

    Temporarily substitutes ``param_vals``/``aux_vals`` (jax arrays or
    tracers) into the Parameters, runs the eager forward with autograd
    recording off, collects aux-state updates (``update_aux``) functionally,
    and restores the originals.  Returns (list of output jax values,
    new aux values aligned with ``aux_params``).

    This is the bridge from the imperative Block world to jax transforms —
    used by hybridize (jit), the SPMD train step (jit over a mesh), and
    anything else that needs grad/vmap of a Block.
    """
    all_params = list(params) + list(aux_params)
    all_vals = list(param_vals) + list(aux_vals)
    aux_ids = [id(p) for p in aux_params]
    saved = [p._data._data for p in all_params]
    coll = {}
    prev_coll = getattr(_trace, "collector", None)
    try:
        for p, v in zip(all_params, all_vals):
            p._data._set_data(v)
        _trace.collector = coll
        with _ag.pause(train_mode=training), _random.trace_stream(rng_key):
            out = block._forward_eager(*inputs_nd) \
                if isinstance(block, HybridBlock) else block(*inputs_nd)
    finally:
        _trace.collector = prev_coll
        for p, v in zip(all_params, saved):
            p._data._set_data(v)
    outs = out if isinstance(out, (list, tuple)) else [out]
    out_vals = [o._data for o in outs]
    new_aux = [coll.get(i, v) for i, v in zip(aux_ids, aux_vals)]
    return out_vals, new_aux


# ---------------------------------------------------------------------------
class _CachedGraph:
    """The CachedOp analog: per-(shape/dtype/mode) jitted executables
    (reference: src/imperative/cached_op.cc CachedOp)."""

    def __init__(self, block, static_alloc=False, static_shape=False):
        self.block = block
        self.static_alloc = static_alloc
        self.static_shape = static_shape
        self._cache = {}

    def _key(self, arrs, template, training, recording):
        return (tuple((a.shape, str(a.dtype)) for a in arrs), template,
                training, recording)

    def _param_lists(self):
        params = list(self.block.collect_params().values())
        trainable = [p for p in params if p.grad_req != "null"]
        aux = [p for p in params if p.grad_req == "null"]
        return trainable, aux

    def __call__(self, *args):
        with _telemetry.trace_span("cached_op", cat="executor",
                                   block=self.block.name):
            return self._call_impl(*args)

    def _call_impl(self, *args):
        import jax
        inputs = [a for a in args if isinstance(a, NDArray)]
        # non-NDArray positionals (None holes, python literals) are part
        # of the traced program's structure: key the cache on them and
        # re-insert them at their original positions inside the trace —
        # dropping them would misbind later tensor args (e.g. a call
        # shaped (x, mask=None, mem))
        template = tuple("\0nd" if isinstance(a, NDArray) else a
                         for a in args)
        try:
            hash(template)
        except TypeError:
            template = tuple(t if t == "\0nd" else repr(t)
                             for t in template)
        trainable, aux = self._param_lists()
        training = _ag.is_training()
        key = self._key(inputs, template, training, False)

        if key not in self._cache:
            block = self.block
            literals = [a for a in args if not isinstance(a, NDArray)]

            def pure(in_vals, tr_vals, aux_vals, rng_key):
                it_nd = iter([NDArray(v, ctx=i.ctx)
                              for v, i in zip(in_vals, inputs)])
                it_lit = iter(literals)
                nds = [next(it_nd) if isinstance(a, NDArray)
                       else next(it_lit) for a in args]
                out_vals, new_aux = functional_call(
                    block, trainable, tr_vals, aux, aux_vals, nds,
                    training, rng_key)
                return tuple(out_vals), tuple(new_aux)

            self._cache[key] = _telemetry.instrument_jit(
                "cached_op", jax.jit(pure))
        jitted = self._cache[key]

        aux_vals = tuple(p.data()._data for p in aux)
        rng_key = _random.new_key()
        n_out_holder = {}

        def call_fn(*arrs):
            ins = arrs[:len(inputs)]
            trs = arrs[len(inputs):]
            out_vals, new_aux = jitted(ins, trs, aux_vals, rng_key)
            n_out_holder["n"] = len(out_vals)
            return tuple(out_vals) + tuple(new_aux)

        res = _invoke(call_fn,
                      list(inputs) + [p.data() for p in trainable],
                      name=f"CachedOp[{self.block.name}]")
        res = res if isinstance(res, list) else [res]
        n_out = n_out_holder["n"]
        outs, new_aux = res[:n_out], res[n_out:]
        for p, v in zip(aux, new_aux):
            p._data._set_data(v._data)
        if len(outs) == 1:
            return outs[0]
        return tuple(outs)


class HybridBlock(Block):
    """Block whose forward is written against the dual eager/traced API
    (reference: gluon.HybridBlock with hybrid_forward(F, x, ...))."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graph = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        self._active = active
        self._cached_graph = (_CachedGraph(self, static_alloc, static_shape)
                              if active else None)
        # children run inline inside this block's trace; their own caches
        # stay whatever the user set, we only propagate when deactivating
        for child in self._children.values():
            if not active:
                child.hybridize(False, **kwargs)

    def infer_shape(self, *args):
        """Layer-specific deferred-shape completion hook.  Layers with
        in_units/in_channels=0 params override this (reference: HybridBlock
        infer_shape via symbolic inference)."""
        raise MXNetError(
            f"{self.__class__.__name__} has deferred-shape parameters but "
            "does not implement infer_shape; initialize with explicit "
            "input dims")

    def _params_kwargs(self):
        kw = {}
        for name, p in self._reg_params.items():
            kw[name] = p.data()
        return kw

    def _forward_eager(self, *args):
        from .. import ndarray as F
        try:
            kw = self._params_kwargs()
        except DeferredInitializationError:
            self.infer_shape(*args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            kw = self._params_kwargs()
        return self.hybrid_forward(F, *args, **kw)

    def _forward_symbolic(self, *args):
        """Trace this block into a Symbol graph: parameters become
        variables named by their global names (reference: HybridBlock's
        dual ndarray/symbol dispatch of hybrid_forward(F, ...))."""
        from .. import symbol as F
        kw = {name: F.var(p.name) for name, p in self._reg_params.items()}
        return self.hybrid_forward(F, *args, **kw)

    def forward(self, *args):
        from ..symbol.symbol import Symbol
        if args and isinstance(args[0], Symbol):
            return self._forward_symbolic(*args)
        if self._active and self._cached_graph is not None \
                and getattr(_trace, "collector", None) is None:
            # ensure deferred shapes are settled before tracing
            for p in self.collect_params().values():
                if p._deferred_init is not None:
                    return self._forward_eager(*args)
            return self._cached_graph(*args)
        return self._forward_eager(*args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def to_symbol(self, *input_names):
        """Trace to a Symbol over named variable inputs (the graph the
        reference gets from hybrid_forward's symbol dispatch)."""
        from .. import symbol as sym_mod
        names = input_names or ("data",)
        return self(*[sym_mod.var(n) for n in names])

    def export(self, path, epoch=0, input_names=("data",)):
        """Serialize for deployment (reference: HybridBlock.export →
        json+params pair: ``path-symbol.json`` + ``path-NNNN.params``).
        Multi-input blocks pass their input names via ``input_names``."""
        from ..ndarray import utils as nd_utils
        sym = self.to_symbol(*input_names)
        sym.save(f"{path}-symbol.json")
        # keys are the SYMBOL arg/aux names split by prefix exactly like
        # model.save_checkpoint, so Module.load restores aux states too
        aux_names = set(sym.list_auxiliary_states())
        nd_utils.save(
            f"{path}-{epoch:04d}.params",
            {("aux:" if name in aux_names else "arg:") + name: p.data()
             for name, p in self.collect_params().items()})

    def optimize_for(self, x, *args, backend=None, **kwargs):
        self.hybridize(True)
        return self(x, *args)


class SymbolBlock(HybridBlock):
    """Construct a Block from a Symbol graph (reference: gluon.SymbolBlock).
    Implemented with the symbol layer; see incubator_mxnet_tpu/symbol."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        from ..ndarray import utils as nd_utils
        sym = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            loaded = nd_utils.load(param_file)
            ret._attach_params({k.split(":", 1)[-1]: v
                                for k, v in loaded.items()})
        return ret

    def _attach_params(self, values):
        """Register name→NDArray values as this block's Parameters (used
        by imports and the ONNX importer)."""
        for name, v in values.items():
            p = Parameter(name, shape=v.shape, dtype=v.dtype)
            p.set_data(v)
            self._params._params[name] = p
            self._reg_params[name] = p

    def _forward_eager(self, *args):
        from ..symbol.symbol import eval_graph
        from .. import autograd as _ag
        bindings = {n: a for n, a in zip(
            [i.name for i in self._inputs], args)}
        for name, p in self._params.items():
            bindings[name] = p.data()
        outs = eval_graph(self._outputs, bindings, _ag.is_training())
        return outs[0] if len(outs) == 1 else outs

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
