"""``mx.gluon.data`` (reference: python/mxnet/gluon/data/)."""
from .dataset import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .dataloader import *  # noqa: F401,F403
from . import vision  # noqa: F401
from .dataset import __all__ as _d
from .sampler import __all__ as _s
from .dataloader import __all__ as _l

__all__ = list(_d) + list(_s) + list(_l) + ["vision"]
