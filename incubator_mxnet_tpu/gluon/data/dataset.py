"""Datasets (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

import os

import numpy as _np

from ...base import MXNetError

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        """Every num_shards-th sample starting at index (reference:
        Dataset.shard — the multi-host data split)."""
        if index >= num_shards:
            raise MXNetError("shard index out of range")
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return SimpleDataset([self[i] for i in range(start, end)])

    def take(self, count):
        return SimpleDataset([self[i]
                              for i in range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*args):
            if len(args) == 1:
                return fn(args[0])
            return (fn(args[0]),) + args[1:]
        return self.transform(first, lazy)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset, fn):
        self._dataset = dataset
        self._fn = fn

    def __len__(self):
        return len(self._dataset)

    def __getitem__(self, idx):
        item = self._dataset[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    """Zip of arrays/datasets (reference: ArrayDataset)."""

    def __init__(self, *args):
        if not args:
            raise MXNetError("needs at least 1 array")
        self._length = len(args[0])
        self._data = []
        for a in args:
            if len(a) != self._length:
                raise MXNetError("all arrays must have the same length")
            from ...ndarray.ndarray import NDArray
            if isinstance(a, NDArray):
                a = a.asnumpy()
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference: RecordFileDataset;
    format: dmlc RecordIO — see io/recordio.py)."""

    def __init__(self, filename):
        from ...io.recordio import IndexedRecordIO
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = IndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
