"""Samplers (reference: python/mxnet/gluon/data/sampler.py)."""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError

__all__ = ["Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "FilterSampler", "IntervalSampler"]


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        from ... import random as mxrand
        indices = _np.arange(self._length)
        mxrand.numpy_rng().shuffle(indices)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    def __init__(self, fn, dataset):
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class IntervalSampler(Sampler):
    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise MXNetError("interval must be <= length")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """Group a sampler into batches (reference: BatchSampler;
    last_batch: keep|discard|rollover)."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(f"invalid last_batch {last_batch!r}")
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) \
                // self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        return (len(self._sampler) + len(self._prev)) // self._batch_size
