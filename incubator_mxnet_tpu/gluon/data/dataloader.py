"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

TPU-native re-design of the worker model.  The reference forks
multiprocessing workers that build batches in POSIX shared memory
(cpu_shared context, reference: src/storage/cpu_shared_storage_manager.h
+ _MultiWorkerIter) and passes fds over sockets.  Here:

* ``num_workers>0`` forks worker PROCESSES (default, reference parity) —
  each worker runs ``dataset[idx]`` + batchify to NUMPY (workers never
  touch jax: the single-client TPU tunnel and XLA state stay owned by the
  parent), batches come back over pipes, and the parent does the one
  ``device_put``.  Fork inheritance replaces fd-passing — the dataset is
  inherited, not pickled per task.
* ``thread_pool=True`` keeps the round-2 prefetching thread pool
  (decode/augment in numpy/PIL releases the GIL) for workloads where fork
  is undesirable.

Start method is FORK deliberately: spawn would re-run sitecustomize's jax
import in every worker and contend for the single-client TPU tunnel.
Workers never call jax (numpy-only contract above), which is what jax's
fork-deadlock warning is about; ``thread_pool=True`` is the escape hatch
if a platform makes fork unsafe.
"""
from __future__ import annotations

import multiprocessing
import time as _time
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ...base import MXNetError
from ... import fault as _fault
from ... import telemetry as _telemetry
from ...ndarray import ndarray as _ndmod
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from . import sampler as _sampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ...ndarray import ops as _ops
        return _ops.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    if arr.dtype == _np.int64:
        arr = arr.astype(_np.int32)
    return _ndmod.array(arr, dtype=arr.dtype)


def default_mp_batchify_fn(data):
    """Worker-side batchify: stacks to NUMPY only (reference:
    default_mp_batchify_fn builds cpu_shared NDArrays; here the no-jax-in-
    workers rule means numpy over the pipe, one device_put in the parent)."""
    if isinstance(data[0], NDArray):
        # the dataset produced device arrays INSIDE a forked worker —
        # that breaks the no-jax-in-workers contract fork depends on
        # (deadlock risk); fail loudly with the two safe spellings
        raise MXNetError(
            "Dataset returned NDArray under num_workers>0: worker "
            "processes must stay jax-free. Return numpy from "
            "__getitem__/transform, or use thread_pool=True")
    if isinstance(data[0], (tuple, list)):
        return tuple(default_mp_batchify_fn(list(x)) for x in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    if arr.dtype == _np.int64:
        arr = arr.astype(_np.int32)
    return arr


def _to_device(batch):
    """Parent-side: numpy → NDArray (the single host→device hop)."""
    if isinstance(batch, (tuple, list)):
        return tuple(_to_device(b) for b in batch)
    if isinstance(batch, _np.ndarray):
        return _ndmod.array(batch, dtype=batch.dtype)
    return batch


# worker globals, inherited through fork (reference: _worker_initializer)
_worker_dataset = None
_worker_batchify = None


def _worker_initializer():
    pass  # dataset/batchify arrive via fork-inherited module globals


def _worker_fn(indices):
    return _worker_batchify([_worker_dataset[i] for i in indices])


class DataLoader:
    """Mini-batch iterator over a Dataset (reference: DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size is required unless batch_sampler is given")
            if sampler is None:
                sampler = (_sampler.RandomSampler(len(dataset)) if shuffle
                           else _sampler.SequentialSampler(len(dataset)))
            elif shuffle:
                raise MXNetError("shuffle is mutually exclusive w/ sampler")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch are mutually "
                "exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._thread_pool = thread_pool
        self._timeout = timeout
        if thread_pool:
            self._batchify_fn = batchify_fn or default_batchify_fn
        else:
            self._batchify_fn = batchify_fn or (
                default_mp_batchify_fn if self._num_workers > 0
                else default_batchify_fn)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def prefetch_to_device(self, buffers=None, placement=None):
        """Wrap this loader in an :class:`io.prefetch.DevicePrefetcher`:
        a background thread stages fetch AND h2d transfer ``buffers``
        batches ahead (``MXNET_PREFETCH_BUFFERS``, default 2), so batch
        i+1 lands on device while batch i computes.  ``placement`` maps
        each array to its device form (e.g. a trainer's mesh sharding);
        default plain ``jax.device_put``.  See docs/performance.md."""
        from ...io.prefetch import DevicePrefetcher
        return DevicePrefetcher(self, buffers=buffers,
                                placement=placement)

    def __iter__(self):
        it = self._iter_impl()
        observe = bool(_telemetry.DATALOADER.subscribers)
        if not observe and not _telemetry.tracer.active:
            yield from it
            return
        # fetch-wait plane: time the consumer spends blocked obtaining the
        # next batch (worker stalls surface here, compute does not); the
        # same window is a "dataloader.fetch" span in the trace
        while True:
            t0 = _time.perf_counter()
            with _telemetry.trace_span("dataloader.fetch", cat="data"):
                try:
                    batch = next(it)
                except StopIteration:
                    return
            if observe:
                _telemetry.DATALOADER.publish(
                    seconds=_time.perf_counter() - t0)
            yield batch

    def _iter_impl(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                _fault.inject("dataloader.fetch")
                yield self._make_batch(indices)
            return
        if self._thread_pool:
            yield from self._iter_threaded()
        else:
            yield from self._iter_multiprocess()

    def _fallback_batch(self, indices, exc):
        """A worker crashed or its result is unusable: rebuild the batch
        in-process so the epoch survives (graceful degradation — one slow
        batch instead of a dead run).  Publishes a FAULT fallback event so
        ``mxtpu_dataloader_fallbacks`` records the rescue."""
        import logging
        logging.getLogger(__name__).warning(
            "dataloader worker failed (%s: %s); rebuilding batch of %d "
            "samples in-process", type(exc).__name__, exc, len(indices))
        _telemetry.FAULT.publish(site="dataloader.fetch", event="fallback")
        return self._make_batch(indices)

    def _iter_threaded(self):
        # prefetching pool: keep `prefetch` batch futures in flight
        with ThreadPoolExecutor(self._num_workers) as pool:
            batches = iter(self._batch_sampler)
            inflight = []
            try:
                for _ in range(max(1, self._prefetch)):
                    indices = next(batches)
                    inflight.append(
                        (pool.submit(self._make_batch, indices), indices))
            except StopIteration:
                pass
            while inflight:
                fut, indices = inflight.pop(0)
                try:
                    nxt = next(batches)
                    inflight.append(
                        (pool.submit(self._make_batch, nxt), nxt))
                except StopIteration:
                    pass
                try:
                    _fault.inject("dataloader.fetch")
                    batch = fut.result()
                except Exception as exc:     # noqa: BLE001 — rescue any
                    batch = self._fallback_batch(indices, exc)
                yield batch

    def _iter_multiprocess(self):
        """Reference _MultiWorkerIter flow: dispatch index batches to forked
        workers, keep `prefetch` in flight, reorder-free FIFO collection.
        A crashed/hung worker result falls back to an in-process rebuild of
        the same index batch (order and content preserved)."""
        global _worker_dataset, _worker_batchify
        ctx = multiprocessing.get_context("fork")
        _worker_dataset = self._dataset
        _worker_batchify = self._batchify_fn
        pool = ctx.Pool(self._num_workers, initializer=_worker_initializer)
        try:
            batches = iter(self._batch_sampler)
            inflight = []
            try:
                for _ in range(max(1, self._prefetch)):
                    indices = next(batches)
                    inflight.append(
                        (pool.apply_async(_worker_fn, (indices,)), indices))
            except StopIteration:
                pass
            while inflight:
                res, indices = inflight.pop(0)
                try:
                    nxt = next(batches)
                    inflight.append(
                        (pool.apply_async(_worker_fn, (nxt,)), nxt))
                except StopIteration:
                    pass
                try:
                    _fault.inject("dataloader.fetch")
                    batch = res.get(self._timeout)
                except Exception as exc:     # noqa: BLE001 — rescue any
                    batch = self._fallback_batch(indices, exc)
                yield _to_device(batch)
        finally:
            pool.terminate()
            pool.join()

    def __len__(self):
        return len(self._batch_sampler)
