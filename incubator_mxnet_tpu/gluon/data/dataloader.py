"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

TPU-native re-design of the worker model: the reference forks
multiprocessing workers that build batches in POSIX shared memory
(cpu_shared context, reference: src/storage/cpu_shared_storage_manager.h)
and passes fds over sockets.  Here host batches are numpy until the single
``device_put`` at the end, so worker parallelism is a prefetching thread
pool (decode/augment is numpy/PIL releasing the GIL) — no fd plumbing, and
the jax transfer guard keeps device placement on the main thread.
``num_workers>0`` controls the prefetch pool size with the same API.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ...base import MXNetError
from ...ndarray import ndarray as _ndmod
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from . import sampler as _sampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        from ...ndarray import ops as _ops
        return _ops.stack(*data, axis=0)
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    if arr.dtype == _np.int64:
        arr = arr.astype(_np.int32)
    return _ndmod.array(arr, dtype=arr.dtype)


default_mp_batchify_fn = default_batchify_fn  # shm path not needed


class DataLoader:
    """Mini-batch iterator over a Dataset (reference: DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False,
                 sampler=None, last_batch=None, batch_sampler=None,
                 batchify_fn=None, num_workers=0, pin_memory=False,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size is required unless batch_sampler is given")
            if sampler is None:
                sampler = (_sampler.RandomSampler(len(dataset)) if shuffle
                           else _sampler.SequentialSampler(len(dataset)))
            elif shuffle:
                raise MXNetError("shuffle is mutually exclusive w/ sampler")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch are mutually "
                "exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        # prefetching pool: keep `prefetch` batch futures in flight
        with ThreadPoolExecutor(self._num_workers) as pool:
            batches = iter(self._batch_sampler)
            inflight = []
            try:
                for _ in range(max(1, self._prefetch)):
                    inflight.append(pool.submit(self._make_batch,
                                                next(batches)))
            except StopIteration:
                pass
            while inflight:
                fut = inflight.pop(0)
                try:
                    inflight.append(pool.submit(self._make_batch,
                                                next(batches)))
                except StopIteration:
                    pass
                yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)
