"""Vision transforms (reference:
python/mxnet/gluon/data/vision/transforms.py).  Operate on HWC uint8/float
NDArrays; ToTensor converts to CHW float32/255."""
from __future__ import annotations

import numpy as _np

from ....base import MXNetError
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomGray", "RandomLighting", "CropResize"]


class Compose(Sequential):
    """Chain transforms (reference: transforms.Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor)."""

    def hybrid_forward(self, F, x):
        x = F.cast(x, "float32") / 255.0
        if x.ndim == 3:
            return x.transpose((2, 0, 1))
        return x.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        from ....ndarray import ndarray as _ndmod
        mean = _np.asarray(self._mean, _np.float32).reshape(-1, 1, 1)
        std = _np.asarray(self._std, _np.float32).reshape(-1, 1, 1)
        return (x - _ndmod.array(mean)) / _ndmod.array(std)


def _resize_np(img, size, interp="bilinear"):
    """Bilinear resize on HWC numpy (no cv2 dependency)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        # short-edge resize keeping aspect (reference Resize(int))
        if h < w:
            new_h, new_w = size, int(w * size / h)
        else:
            new_h, new_w = int(h * size / w), size
    else:
        new_w, new_h = size  # reference order (w, h)
    ys = _np.linspace(0, h - 1, new_h)
    xs = _np.linspace(0, w - 1, new_w)
    y0 = _np.floor(ys).astype(int)
    x0 = _np.floor(xs).astype(int)
    y1 = _np.minimum(y0 + 1, h - 1)
    x1 = _np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype(_np.float32)
    out = (img[y0][:, x0] * (1 - wy) * (1 - wx)
           + img[y0][:, x1] * (1 - wy) * wx
           + img[y1][:, x0] * wy * (1 - wx)
           + img[y1][:, x1] * wy * wx)
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        from ....ndarray import ndarray as _ndmod
        img = x.asnumpy()
        dtype = img.dtype
        out = _resize_np(img, self._size)
        if dtype == _np.uint8:
            out = _np.clip(_np.rint(out), 0, 255).astype(_np.uint8)
        return _ndmod.array(out, dtype=out.dtype)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        from ....ndarray import ndarray as _ndmod
        img = x.asnumpy()
        w, h = self._size
        hh, ww = img.shape[:2]
        y0 = max(0, (hh - h) // 2)
        x0 = max(0, (ww - w) // 2)
        out = img[y0:y0 + h, x0:x0 + w]
        if out.shape[:2] != (h, w):
            out = _resize_np(out, (w, h)).astype(img.dtype)
        return _ndmod.array(out, dtype=out.dtype)


class CropResize(Block):
    def __init__(self, x, y, width, height, size=None, interpolation=None):
        super().__init__()
        self._args = (x, y, width, height)
        self._size = size

    def forward(self, data):
        from ....ndarray import ndarray as _ndmod
        x0, y0, w, h = self._args
        img = data.asnumpy()[y0:y0 + h, x0:x0 + w]
        if self._size:
            img = _resize_np(img, self._size).astype(img.dtype)
        return _ndmod.array(img, dtype=img.dtype)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from ....ndarray import ndarray as _ndmod
        from .... import random as mxrand
        rng = mxrand.numpy_rng()
        img = x.asnumpy()
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = rng.uniform(*self._scale) * area
            ar = rng.uniform(*self._ratio)
            new_w = int(round(_np.sqrt(target_area * ar)))
            new_h = int(round(_np.sqrt(target_area / ar)))
            if new_w <= w and new_h <= h:
                x0 = rng.integers(0, w - new_w + 1)
                y0 = rng.integers(0, h - new_h + 1)
                crop = img[y0:y0 + new_h, x0:x0 + new_w]
                out = _resize_np(crop, self._size).astype(_np.float32)
                if img.dtype == _np.uint8:
                    out = _np.clip(_np.rint(out), 0, 255).astype(_np.uint8)
                return _ndmod.array(out, dtype=out.dtype)
        # fallback: center crop
        return CenterCrop(self._size)(x)


class _RandomFlip(Block):
    def __init__(self, axis):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from .... import random as mxrand
        if mxrand.numpy_rng().random() < 0.5:
            return x.flip(axis=self._axis)
        return x


class RandomFlipLeftRight(_RandomFlip):
    def __init__(self):
        super().__init__(1)


class RandomFlipTopBottom(_RandomFlip):
    def __init__(self):
        super().__init__(0)


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        from .... import random as mxrand
        f = 1.0 + mxrand.numpy_rng().uniform(-self._b, self._b)
        return (x.astype(_np.float32) * f).clip(0, 255).astype(x.dtype)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        from .... import random as mxrand
        f = 1.0 + mxrand.numpy_rng().uniform(-self._c, self._c)
        xf = x.astype(_np.float32)
        mean = xf.mean()
        return ((xf - mean) * f + mean).clip(0, 255).astype(x.dtype)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        from .... import random as mxrand
        f = 1.0 + mxrand.numpy_rng().uniform(-self._s, self._s)
        xf = x.astype(_np.float32)
        gray = xf.mean(axis=-1, keepdims=True)
        return (gray + (xf - gray) * f).clip(0, 255).astype(x.dtype)


class RandomHue(Block):
    """Random hue rotation by up to ±hue (reference: RandomHue; the
    reference's YIQ-rotation formulation)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        from .... import random as mxrand
        from ....ndarray import ndarray as _ndmod
        f = mxrand.numpy_rng().uniform(-self._h, self._h)
        if f == 0.0:
            return x
        theta = _np.pi * f
        # YIQ rotation (same matrix family the reference image_aug uses);
        # the RGB<-YIQ side uses the exact inverse so f->0 is identity
        u, w = _np.cos(theta), _np.sin(theta)
        t_yiq = _np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], _np.float32)
        t_rgb = _np.linalg.inv(t_yiq).astype(_np.float32)
        rot = _np.array([[1, 0, 0], [0, u, -w], [0, w, u]], _np.float32)
        m = t_rgb @ rot @ t_yiq
        out = x.asnumpy().astype(_np.float32) @ m.T
        return _ndmod.array(out.clip(0, 255)).astype(x.dtype)


class RandomGray(Block):
    """Convert to 3-channel grayscale with probability p (reference:
    contrib-era RandomGray / torchvision parity)."""

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        from .... import random as mxrand
        if mxrand.numpy_rng().uniform() >= self._p:
            return x
        xf = x.astype(_np.float32)
        gray = (xf * _np.array([0.299, 0.587, 0.114],
                               _np.float32)).sum(axis=-1, keepdims=True)
        return gray.broadcast_to(x.shape).astype(x.dtype)


class RandomColorJitter(Block):
    """Apply brightness/contrast/saturation/hue jitter in random order
    (reference: RandomColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness > 0:
            self._ts.append(RandomBrightness(brightness))
        if contrast > 0:
            self._ts.append(RandomContrast(contrast))
        if saturation > 0:
            self._ts.append(RandomSaturation(saturation))
        if hue > 0:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        from .... import random as mxrand
        order = mxrand.numpy_rng().permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference: RandomLighting)."""

    _eigval = _np.array([55.46, 4.794, 1.148], _np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        from .... import random as mxrand
        from ....ndarray import ndarray as _ndmod
        rng = mxrand.numpy_rng()
        alpha = rng.normal(0, self._alpha, 3).astype(_np.float32)
        noise = (self._eigvec * alpha * self._eigval).sum(axis=1)
        out = x.asnumpy().astype(_np.float32) + noise
        return _ndmod.array(out, dtype=_np.float32)
