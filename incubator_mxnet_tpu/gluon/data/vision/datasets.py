"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py).

Readers parse the standard on-disk formats (MNIST idx, CIFAR binary) from a
``root`` directory.  Downloading is environment-dependent; with no network
the constructor raises a clear error pointing at ``root``.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from ....base import MXNetError
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset"]


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError(f"bad idx image magic in {path}")
        data = _np.frombuffer(f.read(), dtype=_np.uint8)
        return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError(f"bad idx label magic in {path}")
        return _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        from ....ndarray import ndarray as _ndmod
        img = _ndmod.array(self._data[idx], dtype=_np.uint8)
        label = int(self._label[idx])
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """reference: gluon.data.vision.MNIST (idx format under root)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _find(self, base):
        for cand in (base, base + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise MXNetError(
            f"{base} not found under {self._root}; download is unavailable "
            "in this environment — place the standard files there")

    def _get_data(self):
        imgs, labels = (self._train_files if self._train
                        else self._test_files)
        self._data = _read_idx_images(self._find(imgs))
        self._label = _read_idx_labels(self._find(labels))


class FashionMNIST(MNIST):
    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """reference: gluon.data.vision.CIFAR10 (binary batches under root)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as f:
            raw = _np.frombuffer(f.read(), dtype=_np.uint8)
        rec = raw.reshape(-1, 3073)
        labels = rec[:, 0].astype(_np.int32)
        data = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return data, labels

    def _get_data(self):
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
                 if self._train else ["test_batch.bin"])
        data, labels = [], []
        for n in names:
            p = os.path.join(self._root, n)
            if not os.path.exists(p):
                p2 = os.path.join(self._root, "cifar-10-batches-bin", n)
                if os.path.exists(p2):
                    p = p2
                else:
                    raise MXNetError(
                        f"{n} not found under {self._root}; download is "
                        "unavailable — place CIFAR-10 binary batches there")
            d, l = self._read_batch(p)
            data.append(d)
            labels.append(l)
        self._data = _np.concatenate(data)
        self._label = _np.concatenate(labels)


class CIFAR100(CIFAR10):
    def __init__(self, root="~/.mxnet/datasets/cifar100",
                 fine_label=False, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as f:
            raw = _np.frombuffer(f.read(), dtype=_np.uint8)
        rec = raw.reshape(-1, 3074)
        labels = rec[:, 1 if self._fine else 0].astype(_np.int32)
        data = rec[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return data, labels

    def _get_data(self):
        name = "train.bin" if self._train else "test.bin"
        p = os.path.join(self._root, name)
        if not os.path.exists(p):
            raise MXNetError(f"{name} not found under {self._root}")
        self._data, self._label = self._read_batch(p)


class ImageFolderDataset(Dataset):
    """A dataset of images arranged root/category/image.jpg
    (reference: ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = (".jpg", ".jpeg", ".png", ".bmp")
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(self._exts):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from ....image import imread
        img = imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
