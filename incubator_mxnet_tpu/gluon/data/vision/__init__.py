"""``mx.gluon.data.vision`` (reference:
python/mxnet/gluon/data/vision/)."""
from . import transforms  # noqa: F401
from .datasets import *  # noqa: F401,F403
from .datasets import __all__ as _d

__all__ = list(_d) + ["transforms"]
