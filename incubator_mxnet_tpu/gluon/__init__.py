"""Gluon: the define-by-run frontend (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import rnn
from . import data
from . import loss
from . import utils
from . import model_zoo
from . import contrib

__all__ = ["Parameter", "Constant", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "rnn", "data", "loss", "utils", "model_zoo", "contrib"]
