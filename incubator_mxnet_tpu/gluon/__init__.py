"""Gluon: the define-by-run frontend (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import rnn
from . import loss
from . import utils

__all__ = ["Parameter", "Constant", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "nn", "rnn", "loss", "utils"]
