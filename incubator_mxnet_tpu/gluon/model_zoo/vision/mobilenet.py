"""MobileNet V1/V2/V3 (reference:
python/mxnet/gluon/model_zoo/vision/mobilenet.py; V3 per Howard et al. 2019).

Depthwise convs map to XLA grouped convolution (feature_group_count), which
the TPU compiler lowers efficiently; ReLU6/hard-swish fuse into the conv
epilogue.
"""
from __future__ import annotations

from ... import nn
from ...block import HybridBlock
from ..model_store import load_pretrained

__all__ = ["MobileNet", "MobileNetV2", "MobileNetV3",
           "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
           "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
           "mobilenet_v2_0_25", "mobilenet_v3_small", "mobilenet_v3_large",
           "get_mobilenet", "get_mobilenet_v2"]


class RELU6(HybridBlock):
    """ReLU6 (reference: RELU6)."""

    def hybrid_forward(self, F, x):
        return F.clip(x, 0, 6)


class HardSigmoid(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.clip(x + 3.0, 0, 6) / 6.0


class HardSwish(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._hsig = HardSigmoid()

    def hybrid_forward(self, F, x):
        return x * self._hsig(x)


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm(scale=True))
    if active:
        out.add(RELU6() if relu6 else nn.Activation("relu"))


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, channels=dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels=channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    """MobileNetV2 inverted-residual bottleneck (reference:
    LinearBottleneck)."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = nn.HybridSequential()
            _add_conv(self.out, in_channels * t, relu6=True)
            _add_conv(self.out, in_channels * t, kernel=3, stride=stride,
                      pad=1, num_group=in_channels * t, relu6=True)
            _add_conv(self.out, channels, active=False, relu6=True)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    """MobileNet V1 (reference: MobileNet)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            with self.features.name_scope():
                _add_conv(self.features, channels=int(32 * multiplier),
                          kernel=3, pad=1, stride=2)
                dw_channels = [int(x * multiplier) for x in
                               [32, 64] + [128] * 2 + [256] * 2
                               + [512] * 6 + [1024]]
                channels = [int(x * multiplier) for x in
                            [64] + [128] * 2 + [256] * 2 + [512] * 6
                            + [1024] * 2]
                strides = [1, 2] * 3 + [1] * 5 + [2, 1]
                for dwc, c, s in zip(dw_channels, channels, strides):
                    _add_conv_dw(self.features, dw_channels=dwc, channels=c,
                                 stride=s)
                self.features.add(nn.GlobalAvgPool2D())
                self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class MobileNetV2(HybridBlock):
    """MobileNet V2 (reference: MobileNetV2)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            with self.features.name_scope():
                _add_conv(self.features, int(32 * multiplier), kernel=3,
                          stride=2, pad=1, relu6=True)
                in_channels_group = [int(x * multiplier) for x in
                                     [32] + [16] + [24] * 2 + [32] * 3
                                     + [64] * 4 + [96] * 3 + [160] * 3]
                channels_group = [int(x * multiplier) for x in
                                  [16] + [24] * 2 + [32] * 3 + [64] * 4
                                  + [96] * 3 + [160] * 3 + [320]]
                ts = [1] + [6] * 16
                strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3
                for in_c, c, t, s in zip(in_channels_group, channels_group,
                                         ts, strides):
                    self.features.add(LinearBottleneck(
                        in_channels=in_c, channels=c, t=t, stride=s))
                last_channels = (int(1280 * multiplier)
                                 if multiplier > 1.0 else 1280)
                _add_conv(self.features, last_channels, relu6=True)
                self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            with self.output.name_scope():
                self.output.add(nn.Conv2D(classes, 1, use_bias=False,
                                          prefix="pred_"))
                self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class _SEBlock(HybridBlock):
    """Squeeze-excite with hard-sigmoid gating (MobileNetV3)."""

    def __init__(self, channels, reduction=4, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.pool = nn.GlobalAvgPool2D()
            self.fc1 = nn.Conv2D(channels // reduction, 1, activation="relu")
            self.fc2 = nn.Conv2D(channels, 1)
            self.hsig = HardSigmoid()

    def hybrid_forward(self, F, x):
        w = self.pool(x)
        w = self.fc1(w)
        w = self.hsig(self.fc2(w))
        return x * w


class _V3Bottleneck(HybridBlock):
    """MobileNetV3 bottleneck: expand → dw → (SE) → project."""

    def __init__(self, in_channels, exp_channels, out_channels, kernel,
                 stride, use_se, act, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == out_channels
        act_block = HardSwish if act == "hswish" else None
        with self.name_scope():
            self.out = nn.HybridSequential()
            if exp_channels != in_channels:
                self.out.add(nn.Conv2D(exp_channels, 1, use_bias=False))
                self.out.add(nn.BatchNorm())
                self.out.add(act_block() if act_block
                             else nn.Activation("relu"))
            self.out.add(nn.Conv2D(exp_channels, kernel, stride,
                                   kernel // 2, groups=exp_channels,
                                   use_bias=False))
            self.out.add(nn.BatchNorm())
            self.out.add(act_block() if act_block else nn.Activation("relu"))
            if use_se:
                self.out.add(_SEBlock(exp_channels))
            self.out.add(nn.Conv2D(out_channels, 1, use_bias=False))
            self.out.add(nn.BatchNorm())

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


# (kernel, exp, out, SE, activation, stride)
_V3_LARGE_CFG = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hswish", 2),
    (3, 200, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1),
    (3, 480, 112, True, "hswish", 1),
    (3, 672, 112, True, "hswish", 1),
    (5, 672, 160, True, "hswish", 2),
    (5, 960, 160, True, "hswish", 1),
    (5, 960, 160, True, "hswish", 1),
]
_V3_SMALL_CFG = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hswish", 2),
    (5, 240, 40, True, "hswish", 1),
    (5, 240, 40, True, "hswish", 1),
    (5, 120, 48, True, "hswish", 1),
    (5, 144, 48, True, "hswish", 1),
    (5, 288, 96, True, "hswish", 2),
    (5, 576, 96, True, "hswish", 1),
    (5, 576, 96, True, "hswish", 1),
]


class MobileNetV3(HybridBlock):
    """MobileNet V3 small/large (Howard et al. 2019)."""

    def __init__(self, mode="large", classes=1000, **kwargs):
        super().__init__(**kwargs)
        cfg = _V3_LARGE_CFG if mode == "large" else _V3_SMALL_CFG
        last_exp = 960 if mode == "large" else 576
        last_ch = 1280 if mode == "large" else 1024
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(16, 3, 2, 1, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(HardSwish())
            in_ch = 16
            for k, exp, out, se, act, s in cfg:
                self.features.add(_V3Bottleneck(in_ch, exp, out, k, s, se,
                                                act))
                in_ch = out
            self.features.add(nn.Conv2D(last_exp, 1, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(HardSwish())
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Conv2D(last_ch, 1, use_bias=False))
            self.features.add(HardSwish())
            self.output = nn.HybridSequential(prefix="output_")
            self.output.add(nn.Conv2D(classes, 1))
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def _version_suffix(multiplier):
    # reference naming: '1.0', '0.75', '0.5', '0.25'
    suffix = f"{multiplier:.2f}"
    if suffix.endswith("0"):
        suffix = suffix[:-1]
    return suffix


def get_mobilenet(multiplier, pretrained=False, ctx=None, root=None,
                  **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        load_pretrained(net, f"mobilenet{_version_suffix(multiplier)}",
                        root, ctx)
    return net


def get_mobilenet_v2(multiplier, pretrained=False, ctx=None, root=None,
                     **kwargs):
    net = MobileNetV2(multiplier, **kwargs)
    if pretrained:
        load_pretrained(net, f"mobilenetv2_{_version_suffix(multiplier)}",
                        root, ctx)
    return net


def mobilenet1_0(**kwargs):
    return get_mobilenet(1.0, **kwargs)


def mobilenet0_75(**kwargs):
    return get_mobilenet(0.75, **kwargs)


def mobilenet0_5(**kwargs):
    return get_mobilenet(0.5, **kwargs)


def mobilenet0_25(**kwargs):
    return get_mobilenet(0.25, **kwargs)


def mobilenet_v2_1_0(**kwargs):
    return get_mobilenet_v2(1.0, **kwargs)


def mobilenet_v2_0_75(**kwargs):
    return get_mobilenet_v2(0.75, **kwargs)


def mobilenet_v2_0_5(**kwargs):
    return get_mobilenet_v2(0.5, **kwargs)


def mobilenet_v2_0_25(**kwargs):
    return get_mobilenet_v2(0.25, **kwargs)


def mobilenet_v3_small(pretrained=False, ctx=None, root=None, **kwargs):
    net = MobileNetV3("small", **kwargs)
    if pretrained:
        load_pretrained(net, "mobilenetv3_small", root, ctx)
    return net


def mobilenet_v3_large(pretrained=False, ctx=None, root=None, **kwargs):
    net = MobileNetV3("large", **kwargs)
    if pretrained:
        load_pretrained(net, "mobilenetv3_large", root, ctx)
    return net
