"""Vision model zoo (reference:
python/mxnet/gluon/model_zoo/vision/__init__.py).

Every architecture family the reference ships: ResNet V1/V2 (18/34/50/101/152),
VGG (11/13/16/19, +_bn), AlexNet, DenseNet (121/161/169/201), SqueezeNet
(1.0/1.1), Inception V3, MobileNet V1 (4 multipliers) / V2 (4 multipliers) /
V3 (small/large).

``pretrained=True`` requires weights on local disk (``root=``) — this build
has no network access, so absent files raise rather than download.
"""
from .resnet import *
from .vgg import *
from .alexnet import *
from .densenet import *
from .squeezenet import *
from .inception import *
from .mobilenet import *

from .resnet import __all__ as _resnet_all
from .vgg import __all__ as _vgg_all
from .alexnet import __all__ as _alexnet_all
from .densenet import __all__ as _densenet_all
from .squeezenet import __all__ as _squeezenet_all
from .inception import __all__ as _inception_all
from .mobilenet import __all__ as _mobilenet_all

from ....base import MXNetError

__all__ = (_resnet_all + _vgg_all + _alexnet_all + _densenet_all
           + _squeezenet_all + _inception_all + _mobilenet_all
           + ["get_model"])


# curated factory table (reference: model_zoo/vision/__init__.py models
# dict).  Keys use the reference's spellings (dots: 'squeezenet1.0',
# 'mobilenetv2_1.0'), plus python-identifier aliases for convenience.
_MODELS = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "alexnet": alexnet,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "squeezenet1_0": squeezenet1_0, "squeezenet1_1": squeezenet1_1,
    "inceptionv3": inception_v3, "inception_v3": inception_v3,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenet1_0": mobilenet1_0, "mobilenet0_75": mobilenet0_75,
    "mobilenet0_5": mobilenet0_5, "mobilenet0_25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0,
    "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5,
    "mobilenetv2_0.25": mobilenet_v2_0_25,
    "mobilenet_v2_1_0": mobilenet_v2_1_0,
    "mobilenet_v2_0_75": mobilenet_v2_0_75,
    "mobilenet_v2_0_5": mobilenet_v2_0_5,
    "mobilenet_v2_0_25": mobilenet_v2_0_25,
    "mobilenetv3_small": mobilenet_v3_small,
    "mobilenetv3_large": mobilenet_v3_large,
    "mobilenet_v3_small": mobilenet_v3_small,
    "mobilenet_v3_large": mobilenet_v3_large,
}


def get_model(name, **kwargs):
    """Return a model by name (reference: model_zoo/vision get_model)."""
    name = name.lower()
    if name not in _MODELS:
        raise MXNetError(
            f"Model '{name}' is not supported. Available: "
            f"{sorted(_MODELS.keys())}")
    return _MODELS[name](**kwargs)
