"""VGG (reference: python/mxnet/gluon/model_zoo/vision/vgg.py).

Simonyan & Zisserman.  11/13/16/19-layer configs, with and without BatchNorm.
"""
from __future__ import annotations

from ....base import MXNetError
from ... import nn
from ...block import HybridBlock
from ..model_store import load_pretrained

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn", "get_vgg"]


vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    """VGG network (reference: VGG)."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(nn.Dense(4096, activation="relu",
                                       weight_initializer="normal"))
            self.features.add(nn.Dropout(rate=0.5))
            self.features.add(nn.Dense(4096, activation="relu",
                                       weight_initializer="normal"))
            self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes, weight_initializer="normal")

    def _make_features(self, layers, filters, batch_norm):
        featurizer = nn.HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(nn.Conv2D(filters[i], kernel_size=3,
                                         padding=1))
                if batch_norm:
                    featurizer.add(nn.BatchNorm())
                featurizer.add(nn.Activation("relu"))
            featurizer.add(nn.MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    """Instantiate a VGG (reference: get_vgg)."""
    if num_layers not in vgg_spec:
        raise MXNetError(f"Invalid vgg layers {num_layers}; "
                         f"options {sorted(vgg_spec)}")
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        bn = "_bn" if kwargs.get("batch_norm") else ""
        load_pretrained(net, f"vgg{num_layers}{bn}", root, ctx)
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(11, **kwargs)


def vgg13_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(13, **kwargs)


def vgg16_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(16, **kwargs)


def vgg19_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(19, **kwargs)
