"""Pretrained-weight lookup (reference:
python/mxnet/gluon/model_zoo/model_store.py).

This build has no network access: weights are loaded from local disk only
(``root``, default ``~/.mxnet/models`` like the reference); a missing file
raises instead of downloading.
"""
from __future__ import annotations

import os

from ...base import MXNetError
from ...context import cpu

__all__ = ["load_pretrained", "get_model_file", "DEFAULT_ROOT"]

DEFAULT_ROOT = os.path.join("~", ".mxnet", "models")


def get_model_file(name, root=DEFAULT_ROOT):
    """Return the local path of ``name``'s .params file or raise
    (reference: model_store.get_model_file, minus the download path)."""
    path = os.path.expanduser(os.path.join(root or DEFAULT_ROOT,
                                           f"{name}.params"))
    if not os.path.exists(path):
        raise MXNetError(
            f"Pretrained weights for {name} not found at {path}; this build "
            "has no network access — place a .params file there manually.")
    return path


def load_pretrained(net, name, root=DEFAULT_ROOT, ctx=None):
    net.load_parameters(get_model_file(name, root), ctx=ctx or cpu())
    return net
