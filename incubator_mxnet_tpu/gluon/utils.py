"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..context import Context
from ..ndarray import ndarray as _ndmod
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download", "shape_is_known"]


def split_data(data: NDArray, num_slice: int, batch_axis=0,
               even_split=True):
    """Split along batch axis into num_slice chunks
    (reference: gluon.utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and place on each ctx (reference: gluon.utils.split_and_load).

    TPU-native note: with a single logical mesh the idiomatic path is one
    sharded array, but the per-ctx list API is preserved for parity."""
    if not isinstance(data, NDArray):
        data = _ndmod.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale so joint L2 norm <= max_norm (reference:
    gluon.utils.clip_global_norm)."""
    if not arrays:
        raise MXNetError("no arrays to clip")
    total = 0.0
    for a in arrays:
        n = a.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        import warnings
        warnings.warn("nan or inf found in clip_global_norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


def check_sha1(filename, sha1_hash):
    """True if the file's sha1 matches (reference: gluon.utils.check_sha1)."""
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Download a file (reference: gluon.utils.download).  Zero-egress
    environments: file:// URLs and existing local paths work; http(s)
    uses urllib.  Writes to a temp file and renames atomically so an
    interrupted transfer never poisons the cache path."""
    import os
    import shutil
    import time
    import urllib.error
    import urllib.request
    fname = url.split("/")[-1].split("?")[0]
    if path is None:
        path = fname
    elif os.path.isdir(path):
        path = os.path.join(path, fname)
    if os.path.exists(path) and not overwrite and (
            sha1_hash is None or check_sha1(path, sha1_hash)):
        return path
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".part"
    try:
        if url.startswith("file://"):
            shutil.copyfile(url[len("file://"):], tmp)
        elif os.path.exists(url):
            shutil.copyfile(url, tmp)
        else:
            last = None
            for attempt in range(max(1, retries)):
                try:
                    import ssl
                    ctx = (None if verify_ssl
                           else ssl._create_unverified_context())
                    with urllib.request.urlopen(url, context=ctx) as r, \
                            open(tmp, "wb") as f:
                        shutil.copyfileobj(r, f)
                    last = None
                    break
                except urllib.error.HTTPError as e:
                    if 400 <= e.code < 500:      # permanent — fail fast
                        raise MXNetError(
                            f"download failed for {url!r}: {e}") from e
                    last = e
                    time.sleep(min(2 ** attempt, 8))
                except Exception as e:  # noqa: BLE001 — transient retry
                    last = e
                    time.sleep(min(2 ** attempt, 8))
            if last is not None:
                raise MXNetError(f"download failed for {url!r}: {last}")
        if sha1_hash is not None and not check_sha1(tmp, sha1_hash):
            raise MXNetError(f"downloaded file {path} sha1 mismatch")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return path


def shape_is_known(shape):
    """True if a shape is fully known (reference: mxnet.util
    shape_is_known): the unknown-dim sentinel is -1 under np semantics
    (``npx.set_np()``, where size-0 dims are legal) and 0 in legacy
    mode; a 0-dim shape () is only meaningful under np semantics."""
    if shape is None:
        return False
    from .. import numpy_extension as _npx
    np_mode = _npx.is_np_shape()
    unknown = -1 if np_mode else 0
    if len(shape) == 0:
        return bool(np_mode)
    for d in shape:
        if d is None or d == unknown or d < -1:
            return False
        if not np_mode and d < 0:
            return False
    return True
