"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..context import Context
from ..ndarray import ndarray as _ndmod
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data: NDArray, num_slice: int, batch_axis=0,
               even_split=True):
    """Split along batch axis into num_slice chunks
    (reference: gluon.utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and place on each ctx (reference: gluon.utils.split_and_load).

    TPU-native note: with a single logical mesh the idiomatic path is one
    sharded array, but the per-ctx list API is preserved for parity."""
    if not isinstance(data, NDArray):
        data = _ndmod.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale so joint L2 norm <= max_norm (reference:
    gluon.utils.clip_global_norm)."""
    if not arrays:
        raise MXNetError("no arrays to clip")
    total = 0.0
    for a in arrays:
        n = a.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        import warnings
        warnings.warn("nan or inf found in clip_global_norm")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total
