"""Gluon recurrent layers over the fused RNN op (reference:
python/mxnet/gluon/rnn/rnn_layer.py).

Parameters are registered per (layer, direction) as
``{l|r}{i}_i2h_weight / _h2h_weight / _i2h_bias / _h2h_bias`` exactly like
the reference, and concatenated into the fused op's flat vector at forward
time — so checkpoints are interchangeable and the compute is a single
``lax.scan`` program.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout}")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        ng = _GATES[mode]

        with self.name_scope():
            for layer in range(num_layers):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self._dir
                for d, prefix in zip(range(self._dir), ("l", "r")):
                    name = f"{prefix}{layer}"
                    setattr(self, f"{name}_i2h_weight", self.params.get(
                        f"{name}_i2h_weight",
                        shape=(ng * hidden_size, in_sz),
                        init=i2h_weight_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{name}_h2h_weight", self.params.get(
                        f"{name}_h2h_weight",
                        shape=(ng * hidden_size, hidden_size),
                        init=h2h_weight_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{name}_i2h_bias", self.params.get(
                        f"{name}_i2h_bias", shape=(ng * hidden_size,),
                        init=i2h_bias_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{name}_h2h_bias", self.params.get(
                        f"{name}_h2h_bias", shape=(ng * hidden_size,),
                        init=h2h_bias_initializer,
                        allow_deferred_init=True))

    def _param_names(self):
        names = []
        for layer in range(self._num_layers):
            for prefix in ("l", "r")[:self._dir]:
                names.append(f"{prefix}{layer}")
        return names

    def infer_shape(self, x, *args):
        in_axis = 2 if self._layout == "TNC" else 2
        input_size = x.shape[in_axis]
        ng = _GATES[self._mode]
        for layer in range(self._num_layers):
            in_sz = input_size if layer == 0 \
                else self._hidden_size * self._dir
            for prefix in ("l", "r")[:self._dir]:
                getattr(self, f"{prefix}{layer}_i2h_weight").shape = \
                    (ng * self._hidden_size, in_sz)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial hidden states (reference: _RNNLayer.begin_state)."""
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs)
                          if "shape" in info else func(**kwargs))
        return states

    def hybrid_forward(self, F, x, *states, **params):
        if self._layout == "NTC":
            x = x.transpose((1, 0, 2))
        batch = x.shape[1]
        if not states:
            states = self._auto_states(F, batch)
        elif len(states) == 1 and isinstance(states[0], (list, tuple)):
            states = tuple(states[0])

        # flatten params in fused-op order: all weights, then all biases
        names = self._param_names()
        ws, bs = [], []
        for n in names:
            ws.append(params[f"{n}_i2h_weight"].reshape(-1))
            ws.append(params[f"{n}_h2h_weight"].reshape(-1))
        for n in names:
            bs.append(params[f"{n}_i2h_bias"])
            bs.append(params[f"{n}_h2h_bias"])
        flat = F.concat(*(ws + bs), dim=0)

        out = F.RNN(x, flat, *states, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        output, out_states = out[0], list(out[1:])
        if self._layout == "NTC":
            output = output.transpose((1, 0, 2))
        return output, out_states

    def _auto_states(self, F, batch):
        return tuple(
            F.zeros(info["shape"])
            for info in self.state_info(batch))

    def __call__(self, x, *states):
        out, out_states = super().__call__(x, *states)
        if states:
            return out, out_states
        return out


class RNN(_RNNLayer):
    """Elman RNN (reference: gluon.rnn.RNN; activation relu|tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """reference: gluon.rnn.LSTM (gate order i, f, g, o)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size,
                 self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """reference: gluon.rnn.GRU (gate order r, z, n)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
