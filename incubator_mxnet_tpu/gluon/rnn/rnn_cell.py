"""Recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py).

Cells are fine-grained single-step modules; ``unroll`` runs T steps.  The
eager unroll is a Python loop (each step an async XLA dispatch); under
``hybridize()`` the whole unrolled graph compiles to one program, so the
loop cost vanishes — the TPU answer to the reference's per-step engine
pushes.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell",
           "VariationalDropoutCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        if func is None:
            func = F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(func(shape=info["shape"], **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Run the cell over ``length`` steps (reference:
        RecurrentCell.unroll)."""
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        batch = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch)
        steps = F.split(inputs, length, axis=axis, squeeze_axis=True) \
            if length > 1 else [inputs.squeeze(axis=axis)]
        states = begin_state
        outputs = []
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
        if valid_length is not None:
            outputs = [F.where((valid_length > t).reshape(-1, 1),
                               o, F.zeros_like(o))
                       for t, o in enumerate(outputs)]
        if merge_outputs is False:
            return outputs, states
        stacked = F.stack(*outputs, axis=axis)
        return stacked, states

    def forward(self, x, states):
        self._counter += 1
        return self._fwd(x, states)

    def _fwd(self, x, states):
        # cells execute eagerly; they trace inline when unrolled inside a
        # hybridized parent block
        return self._forward_eager(x, states)


class _BaseRNNCell(RecurrentCell):
    def __init__(self, hidden_size, ngates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ngates * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ngates * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ngates * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ngates * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def infer_shape(self, x, *args):
        ng_h = self.i2h_weight.shape[0]
        self.i2h_weight.shape = (ng_h, x.shape[-1])


class RNNCell(_BaseRNNCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(_BaseRNNCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        h_prev, c_prev = states
        H = self._hidden_size
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * H)
        h2h = F.FullyConnected(h_prev, h2h_weight, h2h_bias,
                               num_hidden=4 * H)
        gates = i2h + h2h
        sl = F.split(gates, 4, axis=1)
        i = F.sigmoid(sl[0])
        f = F.sigmoid(sl[1])
        g = F.tanh(sl[2])
        o = F.sigmoid(sl[3])
        c = f * c_prev + i * g
        h = o * F.tanh(c)
        return h, [h, c]


class GRUCell(_BaseRNNCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, x, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0] if isinstance(states, (list, tuple)) else states
        H = self._hidden_size
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * H)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias, num_hidden=3 * H)
        i2h_sl = F.split(i2h, 3, axis=1)
        h2h_sl = F.split(h2h, 3, axis=1)
        r = F.sigmoid(i2h_sl[0] + h2h_sl[0])
        z = F.sigmoid(i2h_sl[1] + h2h_sl[1])
        n = F.tanh(i2h_sl[2] + r * h2h_sl[2])
        out = (1 - z) * n + z * prev
        return out, [out]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: SequentialRNNCell)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def _fwd(self, x, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            x, new = cell(x, states[p:p + n])
            p += n
            next_states.extend(new)
        return x, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _fwd(self, x, states):
        from ... import ndarray as F
        from ... import autograd as ag
        if self._rate > 0 and ag.is_training():
            x = F.dropout(x, p=self._rate,
                          axes=self._axes if self._axes else None)
        return x, states


class _ModifierCell(RecurrentCell):
    def __init__(self, base_cell, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)


class ZoneoutCell(_ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        super().__init__(base_cell, **kwargs)
        self._zo = zoneout_outputs
        self._zs = zoneout_states
        self._prev_output = None

    def _fwd(self, x, states):
        from ... import ndarray as F
        from ... import autograd as ag
        out, new_states = self.base_cell(x, states)
        if ag.is_training():
            def mask(p, like):
                return F.dropout(F.ones_like(like), p=p) * (1 - p)
            if self._zo > 0:
                prev = self._prev_output if self._prev_output is not None \
                    else F.zeros_like(out)
                m = mask(self._zo, out)
                out = F.where(m, out, prev)
            if self._zs > 0:
                new_states = [F.where(mask(self._zs, ns), ns, s)
                              for ns, s in zip(new_states, states)]
        self._prev_output = out
        return out, new_states


class ResidualCell(_ModifierCell):
    def _fwd(self, x, states):
        out, new_states = self.base_cell(x, states)
        return out + x, new_states


class VariationalDropoutCell(_ModifierCell):
    """Dropout with masks sampled ONCE per sequence and reused across
    time steps (Gal & Ghahramani; reference:
    gluon/rnn/rnn_cell.py VariationalDropoutCell).  Call ``reset()``
    between sequences to draw fresh masks."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        self._di = drop_inputs
        self._ds = drop_states
        self._do = drop_outputs
        super().__init__(base_cell, **kwargs)   # base __init__ resets

    def reset(self):
        super().reset()
        self._mask_in = None
        self._mask_states = None
        self._mask_out = None

    def _mask(self, p, like):
        from ... import ndarray as F
        return F.dropout(F.ones_like(like), p=p)

    def _fwd(self, x, states):
        from ... import autograd as ag
        if ag.is_training():
            if self._di > 0:
                if self._mask_in is None:
                    self._mask_in = self._mask(self._di, x)
                x = x * self._mask_in
            if self._ds > 0:
                if self._mask_states is None:
                    self._mask_states = [self._mask(self._ds, s)
                                         for s in states]
                states = [s * m for s, m in zip(states,
                                                self._mask_states)]
        out, new_states = self.base_cell(x, states)
        if ag.is_training() and self._do > 0:
            if self._mask_out is None:
                self._mask_out = self._mask(self._do, out)
            out = out * self._mask_out
        return out, new_states
    # no unroll override needed: RecurrentCell.unroll resets first, so
    # each unrolled sequence draws fresh masks


class HybridSequentialRNNCell(SequentialRNNCell):
    """Hybrid-capable stack of cells (reference:
    HybridSequentialRNNCell).  Cells here are jit-traceable by
    construction, so this is the same machinery under the reference's
    name."""


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size)
                + self._children["r_cell"].state_info(batch_size))

    def begin_state(self, batch_size=0, **kwargs):
        return (self._children["l_cell"].begin_state(batch_size, **kwargs)
                + self._children["r_cell"].begin_state(batch_size, **kwargs))

    def _fwd(self, x, states):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        batch = inputs.shape[layout.find("N")]
        axis = layout.find("T")
        if begin_state is None:
            begin_state = self.begin_state(batch)
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(
            length, inputs, begin_state[:nl], layout, merge_outputs=True,
            valid_length=valid_length)
        rev = F.flip(inputs, axis=axis) if valid_length is None else \
            F.SequenceReverse(inputs.transpose((1, 0, 2))
                              if layout == "NTC" else inputs,
                              sequence_length=valid_length,
                              use_sequence_length=True)
        if valid_length is not None and layout == "NTC":
            rev = rev.transpose((1, 0, 2))
        r_out, r_states = r_cell.unroll(
            length, rev, begin_state[nl:], layout, merge_outputs=True,
            valid_length=valid_length)
        r_out = F.flip(r_out, axis=axis)
        out = F.concat(l_out, r_out, dim=2)
        return out, l_states + r_states
