"""``mx.gluon.rnn`` (reference: python/mxnet/gluon/rnn/)."""
from .rnn_layer import *  # noqa: F401,F403
from .rnn_cell import *  # noqa: F401,F403
from .rnn_layer import __all__ as _l
from .rnn_cell import __all__ as _c

__all__ = list(_l) + list(_c)
