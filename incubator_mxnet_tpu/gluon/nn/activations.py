"""Activation layers (reference: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish",
           "GELU"]


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation   # before super(): _alias() needs it
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.leaky_relu(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or init_mod.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.maximum(x, 0) + alpha * F.minimum(x, 0)


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.leaky_relu(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.leaky_relu(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.gelu(x)
