"""Convolution & pooling layers (reference:
python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AvgPool1D", "AvgPool2D", "AvgPool3D",
           "GlobalMaxPool1D", "GlobalMaxPool2D", "GlobalMaxPool3D",
           "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]


def _tuplify(x, n):
    return (x,) * n if isinstance(x, int) else tuple(x)


class _Conv(HybridBlock):
    """Shared conv machinery (reference: conv_layers.py _Conv).  weight
    layout (channels, in_channels//groups, *kernel); in_channels=0 defers."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, in_channels, activation, use_bias,
                 weight_initializer, bias_initializer, ndim,
                 op_name="Convolution", adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tuplify(kernel_size, ndim)
        self._strides = _tuplify(strides, ndim)
        self._padding = _tuplify(padding, ndim)
        self._dilation = _tuplify(dilation, ndim)
        self._groups = groups
        self._op_name = op_name
        self._adj = _tuplify(adj, ndim) if adj is not None else None
        self._act_type = activation
        self._ndim = ndim
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, (in_channels // groups)
                          if in_channels else 0) + self._kernel
            else:  # Deconvolution: (in, out/groups, *k)
                wshape = (in_channels, channels // groups) + self._kernel
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x, *args):
        cin = x.shape[1]
        self._in_channels = cin
        if self._op_name == "Convolution":
            self.weight.shape = ((self._channels, cin // self._groups)
                                 + self._kernel)
        else:
            self.weight.shape = ((cin, self._channels // self._groups)
                                 + self._kernel)

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._op_name == "Convolution":
            out = F.Convolution(
                x, weight, bias, kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding,
                num_filter=self._channels, num_group=self._groups,
                no_bias=bias is None)
        else:
            out = F.Deconvolution(
                x, weight, bias, kernel=self._kernel, stride=self._strides,
                dilate=self._dilation, pad=self._padding, adj=self._adj,
                num_filter=self._channels, num_group=self._groups,
                no_bias=bias is None)
        if self._act_type is not None:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return (f"{self.__class__.__name__}({self._in_channels} -> "
                f"{self._channels}, kernel_size={self._kernel}, "
                f"stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 3, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 1,
                         op_name="Deconvolution", adj=output_padding,
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1),
                 padding=(0, 0), output_padding=(0, 0), dilation=(1, 1),
                 groups=1, layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 2,
                         op_name="Deconvolution", adj=output_padding,
                         **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, 3,
                         op_name="Deconvolution", adj=output_padding,
                         **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, ndim, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kernel = _tuplify(pool_size, ndim) if pool_size else None
        self._strides = _tuplify(strides, ndim) if strides else None
        self._padding = _tuplify(padding, ndim)
        self._global = global_pool
        self._pool_type = pool_type
        self._convention = "full" if ceil_mode else "valid"
        self._count_include_pad = count_include_pad

    def hybrid_forward(self, F, x):
        kw = {}
        if self._count_include_pad is not None:
            kw["count_include_pad"] = self._count_include_pad
        return F.Pooling(x, kernel=self._kernel, stride=self._strides,
                         pad=self._padding, pool_type=self._pool_type,
                         global_pool=self._global,
                         pooling_convention=self._convention, **kw)

    def __repr__(self):
        return (f"{self.__class__.__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "max", 1, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "max", 2, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "max", 3, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "avg", 1, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "avg", 2, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(pool_size, strides, padding, ceil_mode, False,
                         "avg", 3, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(None, None, 0, False, True, "max", 1, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__(None, None, 0, False, True, "max", 2, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__(None, None, 0, False, True, "max", 3, **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__(None, None, 0, False, True, "avg", 1, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__(None, None, 0, False, True, "avg", 2, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__(None, None, 0, False, True, "avg", 3, **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        p = _tuplify(padding, 4) if not isinstance(padding, int) \
            else (padding,) * 4
        self._pad = p

    def hybrid_forward(self, F, x):
        pl, pr, pt, pb = (self._pad + self._pad)[:4] \
            if len(self._pad) == 2 else self._pad
        pad_width = ((0, 0), (0, 0), (pt, pb), (pl, pr))
        return F.pad(x, mode="reflect", pad_width=pad_width)
