"""``mx.gluon.nn`` neural-network layers (reference:
python/mxnet/gluon/nn/__init__.py)."""
from .activations import *  # noqa: F401,F403
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from .activations import __all__ as _a
from .basic_layers import __all__ as _b
from .conv_layers import __all__ as _c

__all__ = list(_a) + list(_b) + list(_c)
