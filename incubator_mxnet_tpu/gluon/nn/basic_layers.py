"""Basic Gluon layers (reference: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import Block, HybridBlock, update_aux
from ... import autograd as _ag

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda", "HybridConcatenate", "Concatenate",
           "Identity"]


class Sequential(Block):
    """Stack of Blocks run in order (reference: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                args = tuple(x[1:])
                x = x[0]
        if args:
            return (x,) + args
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        # a plain Sequential of HybridBlocks: hybridize children
        for child in self._children.values():
            child.hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks; hybridizes into ONE fused XLA program
    (reference: nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                args = tuple(x[1:])
                x = x[0]
        if args:
            return (x,) + args
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (reference: nn.Dense).  weight: (units,
    in_units); in_units=0 defers to first forward."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=_np.float32, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x, *args):
        in_units = (int(_np.prod(x.shape[1:])) if self._flatten
                    else x.shape[-1])
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units,
                               flatten=self._flatten)
        if self._act_type is not None:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{self._act_type if self._act_type else 'linear'})")


class Dropout(HybridBlock):
    """reference: nn.Dropout — active only in train mode."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0 and _ag.is_training():
            return F.dropout(x, p=self._rate,
                             axes=self._axes if self._axes else None)
        return F.identity(x)


class Embedding(HybridBlock):
    """reference: nn.Embedding — weight (input_dim, output_dim)."""

    def __init__(self, input_dim, output_dim, dtype=_np.float32,
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)


class BatchNorm(HybridBlock):
    """reference: nn.BatchNorm.  Running stats are aux params updated
    functionally (trace-safe) via ``update_aux``; momentum semantics match
    the reference: moving = moving*momentum + batch*(1-momentum)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,),
                init=gamma_initializer,
                grad_req="write" if scale else "null",
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                grad_req="write" if center else "null",
                allow_deferred_init=True)
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True, differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True, differentiable=False)

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        use_batch_stats = _ag.is_training() and not self._use_global_stats
        if use_batch_stats:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, eps=self._epsilon,
                fix_gamma=not self._scale, axis=self._axis,
                output_mean_var=True)
            m = self._momentum
            update_aux(self.running_mean,
                       (running_mean * m + mean * (1 - m))._data)
            update_aux(self.running_var,
                       (running_var * m + var * (1 - m))._data)
            return out
        return F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            fix_gamma=not self._scale, use_global_stats=True,
            axis=self._axis)


class LayerNorm(HybridBlock):
    """reference: nn.LayerNorm."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                grad_req="write" if scale else "null",
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                grad_req="write" if center else "null",
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                grad_req="write" if scale else "null",
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                grad_req="write" if center else "null",
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                grad_req="write" if scale else "null",
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                grad_req="write" if center else "null",
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        self.gamma.shape = (x.shape[1],)
        self.beta.shape = (x.shape[1],)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.identity(x)


class Lambda(Block):
    """Wrap a function as a Block (reference: nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            function = getattr(F, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else None
        self._func = function

    def hybrid_forward(self, F, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(*args)
        return self._func(F, *args)


class HybridConcatenate(HybridBlock):
    """Run children on the same input, concat outputs (reference 2.x-era
    contrib Concurrent; kept for model-zoo building)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class Concatenate(Block):
    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def forward(self, x):
        from ... import ndarray as F
        outs = [block(x) for block in self._children.values()]
        return F.concat(*outs, dim=self.axis)
