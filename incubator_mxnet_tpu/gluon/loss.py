"""Loss layers (reference: python/mxnet/gluon/loss.py).

Each loss is a HybridBlock; ``weight`` rescales, ``batch_axis`` is the axis
averaged over last, sample_weight broadcasts in — all matching the
reference's ``_apply_weighting`` semantics.  CTCLoss is a log-semiring
``lax.scan`` over the extended label sequence (the reference wraps warp-ctc /
cudnn CTC; reference: src/operator/nn/ctc_loss.cc).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return (f"{self.__class__.__name__}(batch_axis={self._batch_axis}, "
                f"w={self._weight})")

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _mean_all_but_batch(self, F, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return F.mean(loss, axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_all_but_batch(F, loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """reference: SigmoidBCELoss — numerically stable log-sum-exp form."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = F.relu(pred) - pred * label + log_weight * \
                    (F.Activation(-F.abs(pred), act_type="softrelu")
                     + F.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.log(pred + eps) * label * pos_weight
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """reference: SoftmaxCELoss — sparse_label picks, dense does -sum."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(F, loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis + 1 if pred.ndim > 1 else ())
        loss = F.relu(loss + self._margin)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling approximation for log(target!)
            stirling = (target * F.log(target + 1e-12) - target
                        + 0.5 * F.log(2 * _np.pi * (target + 1e-12)))
            stirling = F.where(target <= 1, F.zeros_like(stirling), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos = (F.sum(input1 * input2, axis=-1)
               / (F.norm(input1, axis=-1) * F.norm(input2, axis=-1) + 1e-12))
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class CTCLoss(Loss):
    """Connectionist Temporal Classification (reference: gluon.loss.CTCLoss,
    layout TNC, blank label first or last).

    Implemented as a log-semiring forward (alpha) recursion with
    ``lax.scan`` over time — static shapes, one fused XLA loop, replacing
    the reference's warp-ctc binding.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"bad layout {layout}")
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        from ..ndarray.ndarray import _invoke

        if self._layout == "NTC":
            pred = pred.transpose((1, 0, 2))    # -> TNC
        if self._label_layout == "TN":
            label = label.transpose((1, 0))

        T, N, C = pred.shape
        L = label.shape[1]
        inputs = [pred, label]
        has_pl = pred_lengths is not None
        has_ll = label_lengths is not None
        if has_pl:
            inputs.append(pred_lengths)
        if has_ll:
            inputs.append(label_lengths)

        def ctc(p, lab, *rest):
            import jax
            import jax.numpy as jnp
            from jax import lax
            idx = 0
            pl = rest[idx].astype(jnp.int32) if has_pl else \
                jnp.full((N,), T, jnp.int32)
            idx += int(has_pl)
            ll = rest[idx].astype(jnp.int32) if has_ll else \
                jnp.full((N,), L, jnp.int32)

            logp = jax.nn.log_softmax(p, axis=-1)
            blank = 0
            # extended label seq: blank, l1, blank, l2, ... blank (len 2L+1)
            S = 2 * L + 1
            lab = lab.astype(jnp.int32)
            ext = jnp.full((N, S), blank, jnp.int32)
            ext = ext.at[:, 1::2].set(lab)
            ext_valid = jnp.arange(S)[None, :] < (2 * ll + 1)[:, None]

            # can-skip mask: alpha[s] may come from s-2 when ext[s] != blank
            # and ext[s] != ext[s-2]
            ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)),
                             constant_values=-1)[:, :S]
            can_skip = (ext != blank) & (ext != ext_m2)

            neg_inf = -1e30
            alpha0 = jnp.full((N, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
            alpha0 = alpha0.at[:, 1].set(
                jnp.where(ll > 0,
                          jnp.take_along_axis(
                              logp[0], ext[:, 1:2], axis=1)[:, 0],
                          neg_inf))

            def lse(a, b):
                m = jnp.maximum(a, b)
                return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))

            def step(alpha, logp_t):
                a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                               constant_values=neg_inf)[:, :S]
                a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                               constant_values=neg_inf)[:, :S]
                a = lse(alpha, a_m1)
                a = jnp.where(can_skip, lse(a, a_m2), a)
                emit = jnp.take_along_axis(logp_t, ext, axis=1)
                new = a + emit
                new = jnp.where(ext_valid, new, neg_inf)
                return new, new

            _, alphas = lax.scan(step, alpha0, logp[1:])
            alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
            # pick alpha at t = pl-1, s in {2ll, 2ll-1}
            a_final = jnp.take_along_axis(
                alphas, (pl - 1)[None, :, None], axis=0)[0]  # (N, S)
            end1 = jnp.take_along_axis(a_final, (2 * ll)[:, None],
                                       axis=1)[:, 0]
            end2 = jnp.take_along_axis(
                a_final, jnp.maximum(2 * ll - 1, 0)[:, None], axis=1)[:, 0]
            end2 = jnp.where(ll > 0, end2, neg_inf)
            return -lse(end1, end2)

        loss = _invoke(ctc, inputs, name="CTCLoss")
        return _apply_weighting(F, loss, self._weight, sample_weight)
