"""Shared stdlib HTTP plumbing for the runtime's embedded servers.

Two subsystems expose HTTP endpoints from inside a training/serving
process: the telemetry exporter (``telemetry_http.py``: ``/metrics``,
``/healthz``, ``/trace``) and the model server (``serving/server.py``:
``/v1/models/...``).  Both are stdlib-only ``http.server`` stacks on
daemon threads; this module is the one copy of the plumbing they share
so the two can't drift:

* :class:`BaseJSONHandler` — a ``BaseHTTPRequestHandler`` with the
  common response helpers (``_send``/``send_json``/``read_json``),
  silent request logging (training stdout stays clean), a
  swallow-all error guard so a handler bug degrades to a 500, never a
  crash-looping accept thread, and per-request id handling: every
  response — 200s, 4xx/5xx error branches, even the guard's own
  500 — carries an ``X-Request-Id`` header echoing the client's
  ``x-request-id`` (sanitized) or a freshly generated id, so a client
  can always correlate a response with server-side FAULT events, spans,
  and flight-recorder dumps (docs/observability.md).
* :func:`start_http_server` / :func:`stop_http_server` — daemon-thread
  lifecycle.  Port 0 binds an ephemeral port; the bound port is
  ``server.server_address[1]``.
"""
from __future__ import annotations

import json
import re
import sys
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Type

__all__ = ["BaseJSONHandler", "HTTPServerBase", "start_http_server",
           "stop_http_server", "parse_trace_id"]


class HTTPServerBase(ThreadingHTTPServer):
    """Default server class: daemon handler threads and a listen
    backlog deep enough for a thundering herd of concurrent clients
    (socketserver's default of 5 resets connections under load)."""
    daemon_threads = True
    request_queue_size = 128

    def handle_error(self, request, client_address):
        # A client dropping its half of a keep-alive connection (or an
        # SSE consumer walking away) is business as usual for a server
        # fronted by a router/balancer — not worth a stderr traceback.
        # Anything else keeps socketserver's loud default.
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)


# what survives of a client-supplied x-request-id: word chars, dot,
# dash — anything else is stripped so ids are safe to grep, log, and
# embed in filenames
_REQUEST_ID_JUNK = re.compile(r"[^A-Za-z0-9._\-]")

# the router's traceparent-style header: <trace root>-<hop span id>.
# The trace root is the request id (which may itself contain dashes),
# the hop id is the 8-hex sid of the router span that made this
# upstream call — so the split is on the LAST dash.
_TRACE_ID_RE = re.compile(r"^([A-Za-z0-9._\-]{1,64})-([0-9a-f]{1,16})$")
_TRACE_ID_MAX = 96


def parse_trace_id(raw) -> Optional[tuple]:
    """Parse an ``X-Trace-Id`` header value into ``(trace_id,
    parent_span_id)``.  Anything malformed, oversized, or
    junk-charactered returns ``None`` — propagation is best-effort and a
    hostile/buggy header must never fail the request it rides on."""
    if not raw or not isinstance(raw, str):
        return None
    raw = raw.strip()
    if len(raw) > _TRACE_ID_MAX:
        return None
    m = _TRACE_ID_RE.match(raw)
    return (m.group(1), m.group(2)) if m else None


class BaseJSONHandler(BaseHTTPRequestHandler):
    """Response/request helpers shared by every embedded HTTP server."""

    server_version = "mxtpu-http/1.0"
    # chunked streaming (start_stream) requires HTTP/1.1 framing; every
    # non-streamed response carries Content-Length, so keep-alive is safe
    protocol_version = "HTTP/1.1"

    def request_id(self) -> str:
        """This request's id: the client's ``x-request-id`` header
        (sanitized, capped at 64 chars) or a generated 16-hex-char id.
        Stable for the duration of one request; ``_send`` echoes it on
        the response and resets it for the next keep-alive request."""
        rid = getattr(self, "_mxtpu_request_id", None)
        if rid is None:
            raw = (self.headers.get("x-request-id") or "").strip() \
                if getattr(self, "headers", None) else ""
            rid = _REQUEST_ID_JUNK.sub("", raw)[:64] or uuid.uuid4().hex[:16]
            self._mxtpu_request_id = rid
        return rid

    def trace_parent(self) -> Optional[tuple]:
        """The upstream trace context from this request's ``X-Trace-Id``
        header: ``(trace_id, parent_span_id)``, or ``None`` when absent
        or malformed (see :func:`parse_trace_id`)."""
        if getattr(self, "headers", None) is None:
            return None
        return parse_trace_id(self.headers.get("x-trace-id"))

    def _send(self, code: int, body: str, ctype: str,
              headers: Optional[dict] = None) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("X-Request-Id", self.request_id())
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)
        self._mxtpu_request_id = None   # keep-alive: next request, new id

    def send_text(self, code: int, body: str,
                  ctype: str = "text/plain; charset=utf-8",
                  headers: Optional[dict] = None) -> None:
        self._send(code, body, ctype, headers)

    def send_json(self, code: int, obj,
                  headers: Optional[dict] = None) -> None:
        self._send(code, json.dumps(obj, default=str) + "\n",
                   "application/json", headers)

    def read_body(self) -> bytes:
        """The raw request body (``b""`` when absent).  The router
        reads the body once and forwards the same bytes on every
        failover attempt, so retried requests are byte-identical."""
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length > 0 else b""

    def read_json(self):
        """Parse the request body as JSON (``ValueError`` on garbage;
        an absent/empty body parses as ``{}``)."""
        raw = self.read_body()
        if not raw:
            return {}
        try:
            return json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"request body is not valid JSON: {e}")

    # -- streaming (SSE over chunked transfer) --------------------------
    def start_stream(self, code: int = 200,
                     ctype: str = "text/event-stream",
                     headers: Optional[dict] = None) -> None:
        """Open a chunked streaming response (no ``Content-Length``).
        The ``X-Request-Id`` header rides the stream headers like any
        other response, so streamed requests stay correlatable with
        server-side spans/FAULT events.  Follow with
        :meth:`send_event` calls and finish with :meth:`end_stream`."""
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Request-Id", self.request_id())
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def relay_chunk(self, data: bytes) -> None:
        """Forward already-framed payload bytes (e.g. upstream SSE
        lines) onto an open stream without re-encoding — the router's
        passthrough path.  Same disconnect contract as
        :meth:`send_event`."""
        if data:
            self._write_chunk(data)

    def send_event(self, obj, event: Optional[str] = None) -> None:
        """One SSE event carrying a JSON payload.  Raises
        ``BrokenPipeError``/``ConnectionError`` when the client has gone
        away — callers treat that as a cancel signal."""
        prefix = f"event: {event}\n" if event else ""
        self._write_chunk(
            (prefix + "data: " + json.dumps(obj, default=str)
             + "\n\n").encode("utf-8"))

    def end_stream(self) -> None:
        """Terminate the chunked response (zero-length chunk)."""
        try:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        finally:
            self._mxtpu_request_id = None

    def guard(self, fn) -> None:
        """Run a route handler; an exporter/server bug must not
        500-loop or kill the accept thread."""
        try:
            fn()
        except Exception as e:
            try:
                self.send_text(500, f"server error: {e!r}\n")
            except Exception:
                pass

    def log_message(self, fmt, *args):
        pass                            # stay silent on training stdout


def start_http_server(handler_cls: Type[BaseHTTPRequestHandler],
                      port: int, host: str = "0.0.0.0",
                      name: str = "mxtpu-http",
                      server_cls: Type[ThreadingHTTPServer]
                      = HTTPServerBase) -> ThreadingHTTPServer:
    """Bind ``host:port`` and serve ``handler_cls`` from a daemon thread.
    Raises ``OSError`` when the port cannot be bound.  The serving
    thread is attached to the server object so :func:`stop_http_server`
    can join it."""
    srv = server_cls((host, int(port)), handler_cls)
    srv.daemon_threads = True
    th = threading.Thread(target=srv.serve_forever, name=name, daemon=True)
    th.start()
    srv._mxtpu_thread = th
    return srv


def stop_http_server(srv: Optional[ThreadingHTTPServer],
                     timeout: float = 5.0) -> None:
    """Shut a server started by :func:`start_http_server` down and
    release its port (no-op on ``None``)."""
    if srv is None:
        return
    th = getattr(srv, "_mxtpu_thread", None)
    srv.shutdown()
    srv.server_close()
    if th is not None:
        th.join(timeout=timeout)
