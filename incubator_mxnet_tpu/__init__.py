"""incubator_mxnet_tpu: a TPU-native deep-learning framework with the
capability surface of Apache MXNet 1.x (reference: ciyongch/incubator-mxnet),
re-designed from scratch for JAX/XLA/pjit/Pallas.

Conventional import::

    import incubator_mxnet_tpu as mx

    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x * 2).sum()
    y.backward()

Architecture notes (vs the reference, see SURVEY.md):
  * no C ABI / ctypes layer — Python is the single frontend, XLA the executor
  * no dependency engine — jax async dispatch + XLA scheduling subsume it
  * no storage manager — PJRT owns device memory
  * distribution = jax.sharding over a device Mesh, not parameter servers
"""
__version__ = "0.1.0"

from .base import MXNetError, MXTPUError
from .context import (Context, Device, cpu, gpu, tpu, cpu_pinned, cpu_shared,
                      current_context, current_device, num_gpus, num_tpus)
from . import base
from . import context
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from . import initializer
from . import initializer as init
from . import gluon
from . import optimizer
from . import lr_scheduler
from . import kvstore
from . import kvstore as kv
from . import parallel
from . import symbol
from . import symbol as sym
from .executor import Executor
from . import io
from . import recordio
from . import image
from . import metric
from . import callback
from . import model
from . import visualization
from . import attribute
from .attribute import AttrScope
from . import name
from . import monitor
from .monitor import Monitor
from . import visualization as viz
from . import checkpoint
from . import module
from . import module as mod
from . import numpy as np
from . import numpy_extension as npx
from . import engine
from . import telemetry
from . import fault
from . import serving
from . import profiler
from . import test_utils
from . import library
from .feedforward import FeedForward
from . import runtime
from . import contrib

base.log_compat_env_once()

__all__ = ["MXNetError", "MXTPUError", "Context", "Device", "cpu", "gpu",
           "tpu", "cpu_pinned", "cpu_shared", "current_context",
           "current_device", "num_gpus", "num_tpus", "nd", "ndarray",
           "autograd", "random", "base", "context", "initializer", "init",
           "gluon", "optimizer", "lr_scheduler", "kvstore", "kv",
           "parallel", "symbol", "sym", "Executor", "io", "recordio",
           "image", "metric", "callback", "model", "module", "mod", "np",
           "npx", "engine", "telemetry", "fault", "serving", "profiler",
           "runtime", "contrib"]
