"""Stateful-looking RNG over jax's functional keys.

Re-design of the reference RNG resources (reference: src/resource.cc
ResourceRequest::kRandom, src/common/random_generator.h;
python/mxnet/random.py ``mx.random.seed``).  The reference keeps per-device
stateful generators; here each Context owns a key *stream*: ``seed()`` resets
the stream, every consumer splits the next key off it.  Deterministic given a
seed, parallel-safe, and jit-friendly (keys are values)."""
from __future__ import annotations

import threading

from .context import Context, current_context

__all__ = ["seed", "new_key", "current_key", "numpy_rng", "trace_stream",
           "get_state", "set_state"]

_lock = threading.Lock()
_streams: dict = {}
_DEFAULT_SEED = 0
_tls = threading.local()


class trace_stream:
    """Scope that redirects ``new_key`` to split off a *traced* base key —
    used while tracing a hybridized block under jit so dropout/samplers
    consume a key that is an argument of the compiled program rather than a
    baked-in constant (fresh randomness per call, XLA-visible)."""

    def __init__(self, base_key):
        self._base = base_key

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append([self._base])
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False


def seed(seed_state: int, ctx="all"):
    """Seed the RNG (reference: mx.random.seed(seed, ctx='all'))."""
    global _streams
    import jax
    with _lock:
        if ctx == "all":
            _streams.clear()
            _streams[None] = jax.random.PRNGKey(seed_state)
        else:
            _streams[Context(ctx)] = jax.random.PRNGKey(seed_state)


def _stream_key(ctx):
    # per-context stream if seeded per-context, else the global stream
    if ctx in _streams:
        return ctx
    return None


def new_key(ctx=None):
    """Split the next key off the context's stream."""
    import jax
    stack = getattr(_tls, "stack", None)
    if stack:
        nxt, use = jax.random.split(stack[-1][0])
        stack[-1][0] = nxt
        return use
    ctx = ctx if ctx is not None else current_context()
    with _lock:
        k = _stream_key(ctx)
        if k not in _streams:
            _streams[k] = jax.random.PRNGKey(_DEFAULT_SEED)
        cur = _streams[k]
        nxt, use = jax.random.split(cur)
        _streams[k] = nxt
        return use


def numpy_rng(ctx=None):
    """A numpy Generator advanced off the context's key stream — host-side
    randomness (initializers, data aug) that still obeys ``mx.random.seed``."""
    import numpy as _np
    key = new_key(ctx)
    # fold the 2x uint32 key into a 64-bit numpy seed
    import numpy as np
    kv = np.asarray(key, dtype=np.uint32).reshape(-1)
    s = int(kv[0]) << 32 | int(kv[-1])
    return _np.random.default_rng(s)


def get_state() -> dict:
    """JSON-serializable snapshot of every key stream (checkpoint/resume:
    the manifest carries this so a resumed run continues the SAME key
    sequence instead of replaying or diverging).  Keys map stream name
    ("all" for the global stream, "cpu:0"-style for per-context ones) to
    the raw uint32 key words."""
    import numpy as np
    import jax
    with _lock:
        items = list(_streams.items())
    out = {}
    for ctx, key in items:
        try:
            data = np.asarray(jax.random.key_data(key))
        except Exception:           # already a raw uint32 key array
            data = np.asarray(key)
        name = "all" if ctx is None else \
            f"{ctx.device_type}:{ctx.device_id}"
        out[name] = data.astype(np.uint32).reshape(-1).tolist()
    return out


def set_state(state: dict) -> None:
    """Restore streams captured by :func:`get_state`.  Streams absent
    from ``state`` are dropped (exactly the captured picture comes
    back)."""
    import numpy as np
    import jax.numpy as jnp
    with _lock:
        _streams.clear()
        for name, data in state.items():
            key = jnp.asarray(np.asarray(data, dtype=np.uint32))
            if name == "all":
                _streams[None] = key
            else:
                dev, _, idx = name.partition(":")
                _streams[Context(dev, int(idx or 0))] = key


def current_key(ctx=None):
    import jax
    ctx = ctx if ctx is not None else current_context()
    with _lock:
        k = _stream_key(ctx)
        if k not in _streams:
            _streams[k] = jax.random.PRNGKey(_DEFAULT_SEED)
        return _streams[k]


# reference parity (docs/env_var.md): MXNET_SEED seeds every context's
# stream at import when set.  Only the module-global default changes —
# streams stay lazily created, so no jax backend is initialized at
# import time (users may still configure the platform afterwards).
def _seed_from_env():
    global _DEFAULT_SEED
    from .base import getenv
    v = getenv("MXNET_SEED")
    if v is not None and str(v).strip():
        try:
            _DEFAULT_SEED = int(v)
            _streams.clear()
        except ValueError:
            import logging
            logging.getLogger(__name__).warning(
                "MXNET_SEED=%r is not an integer; ignored", v)


_seed_from_env()
