"""Stateful-looking RNG over jax's functional keys.

Re-design of the reference RNG resources (reference: src/resource.cc
ResourceRequest::kRandom, src/common/random_generator.h;
python/mxnet/random.py ``mx.random.seed``).  The reference keeps per-device
stateful generators; here each Context owns a key *stream*: ``seed()`` resets
the stream, every consumer splits the next key off it.  Deterministic given a
seed, parallel-safe, and jit-friendly (keys are values)."""
from __future__ import annotations

import threading

from .context import Context, current_context

__all__ = ["seed", "new_key", "current_key"]

_lock = threading.Lock()
_streams: dict = {}
_DEFAULT_SEED = 0


def seed(seed_state: int, ctx="all"):
    """Seed the RNG (reference: mx.random.seed(seed, ctx='all'))."""
    global _streams
    import jax
    with _lock:
        if ctx == "all":
            _streams.clear()
            _streams[None] = jax.random.PRNGKey(seed_state)
        else:
            _streams[Context(ctx)] = jax.random.PRNGKey(seed_state)


def _stream_key(ctx):
    # per-context stream if seeded per-context, else the global stream
    if ctx in _streams:
        return ctx
    return None


def new_key(ctx=None):
    """Split the next key off the context's stream."""
    import jax
    ctx = ctx if ctx is not None else current_context()
    with _lock:
        k = _stream_key(ctx)
        if k not in _streams:
            _streams[k] = jax.random.PRNGKey(_DEFAULT_SEED)
        cur = _streams[k]
        nxt, use = jax.random.split(cur)
        _streams[k] = nxt
        return use


def current_key(ctx=None):
    import jax
    ctx = ctx if ctx is not None else current_context()
    with _lock:
        k = _stream_key(ctx)
        if k not in _streams:
            _streams[k] = jax.random.PRNGKey(_DEFAULT_SEED)
        return _streams[k]
