// Native RecordIO core (reference analog: the C++ record reader under
// 3rdparty/dmlc-core/include/dmlc/recordio.h + src/io/ threaded readers —
// re-designed, not translated: one file descriptor + positional pread()
// gives lock-free parallel reads, so the Python-side thread pool scales
// IO without per-thread handles or a GIL-holding seek/read loop).
//
// Framing (byte-compatible with dmlc RecordIO):
//   [kMagic u32le][lrec u32le][payload][pad to 4]
//   lrec = cflag<<29 | length;  cflag: 0 whole, 1 start, 2 middle, 3 end.
//
// C ABI only (loaded via ctypes; pybind11 is not in this image).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xCED7230A;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Reader {
  int fd = -1;
  int64_t size = 0;
};

inline int64_t pad4(int64_t n) { return (4 - n % 4) % 4; }

// read a physical record at `off`; returns cflag, fills payload span and
// advances *next to the following record.  -1 on error/EOF.
int read_physical(const Reader* r, int64_t off, std::vector<uint8_t>* out,
                  int64_t* next) {
  uint8_t head[8];
  if (off + 8 > r->size) return -1;
  if (pread(r->fd, head, 8, off) != 8) return -1;
  uint32_t magic, lrec;
  std::memcpy(&magic, head, 4);
  std::memcpy(&lrec, head + 4, 4);
  if (magic != kMagic) return -2;
  const int cflag = lrec >> 29;
  const int64_t len = lrec & kLenMask;
  if (off + 8 + len > r->size) return -1;
  const size_t prev = out->size();
  out->resize(prev + len);
  if (len > 0 && pread(r->fd, out->data() + prev, len, off + 8) != len)
    return -1;
  *next = off + 8 + len + pad4(len);
  return cflag;
}

// read one LOGICAL record starting at `off` (assembling continuations).
// returns 0 ok / <0 error; fills buf + sets *next.
int read_logical(const Reader* r, int64_t off, std::vector<uint8_t>* buf,
                 int64_t* next) {
  buf->clear();
  int cflag = read_physical(r, off, buf, next);
  if (cflag < 0) return cflag;
  if (cflag == 0) return 0;
  if (cflag != 1) return -3;  // continuation without start
  while (true) {
    cflag = read_physical(r, *next, buf, next);
    if (cflag < 0) return cflag == -1 ? -4 : cflag;  // unterminated
    if (cflag == 3) return 0;
    if (cflag != 2) return -3;
  }
}

}  // namespace

extern "C" {

void* rio_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  auto* r = new Reader();
  r->fd = fd;
  r->size = st.st_size;
  return r;
}

void rio_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  if (!r) return;
  if (r->fd >= 0) close(r->fd);
  delete r;
}

void rio_free(uint8_t* p) { std::free(p); }

// read the logical record at `offset`; *out is malloc'd (rio_free).
// returns 0 ok, <0 error code.
int rio_read_at(void* h, int64_t offset, uint8_t** out, int64_t* out_len) {
  auto* r = static_cast<Reader*>(h);
  std::vector<uint8_t> buf;
  int64_t next;
  int rc = read_logical(r, offset, &buf, &next);
  if (rc != 0) {
    *out = nullptr;
    *out_len = 0;
    return rc;
  }
  *out = static_cast<uint8_t*>(std::malloc(buf.size() ? buf.size() : 1));
  if (*out == nullptr) {
    *out_len = 0;
    return -6;  // allocation failure -> catchable IOError, not a segfault
  }
  std::memcpy(*out, buf.data(), buf.size());
  *out_len = static_cast<int64_t>(buf.size());
  return 0;
}

// scan the file, returning logical-record start offsets.  *out is
// malloc'd (caller frees with rio_free on the cast pointer).  Returns the
// record count, or a negative error code.
int64_t rio_scan_index(const char* path, int64_t** out) {
  void* h = rio_open(path);
  if (!h) return -1;
  auto* r = static_cast<Reader*>(h);
  std::vector<int64_t> offsets;
  std::vector<uint8_t> buf;
  int64_t off = 0;
  while (off < r->size) {
    int64_t next;
    buf.clear();
    int rc = read_logical(r, off, &buf, &next);
    if (rc != 0) {
      // off < size but the record doesn't parse: truncated/corrupt.
      // Return the error so the Python fallback path raises its
      // MXNetError instead of silently training on fewer samples.
      rio_close(h);
      return rc < 0 ? rc : -5;
    }
    offsets.push_back(off);
    off = next;
  }
  rio_close(h);
  *out = static_cast<int64_t*>(
      std::malloc(sizeof(int64_t) * (offsets.empty() ? 1 : offsets.size())));
  if (*out == nullptr) return -6;
  std::memcpy(*out, offsets.data(), sizeof(int64_t) * offsets.size());
  return static_cast<int64_t>(offsets.size());
}

// parallel batched read: n records at offsets[], nthreads workers striding
// over them via pread (no shared cursor → no locking).  bufs[i]/lens[i]
// are filled per record (rio_free each buf).  Returns 0 ok, <0 first error.
int rio_read_many(void* h, const int64_t* offsets, int64_t n,
                  int nthreads, uint8_t** bufs, int64_t* lens) {
  auto* r = static_cast<Reader*>(h);
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n) nthreads = static_cast<int>(n);
  std::vector<int> rcs(nthreads, 0);
  auto work = [&](int t) {
    std::vector<uint8_t> buf;
    for (int64_t i = t; i < n; i += nthreads) {
      int64_t next;
      int rc = read_logical(r, offsets[i], &buf, &next);
      if (rc != 0) {
        rcs[t] = rc;
        bufs[i] = nullptr;
        lens[i] = 0;
        continue;
      }
      bufs[i] = static_cast<uint8_t*>(
          std::malloc(buf.size() ? buf.size() : 1));
      if (bufs[i] == nullptr) {
        rcs[t] = -6;
        lens[i] = 0;
        continue;
      }
      std::memcpy(bufs[i], buf.data(), buf.size());
      lens[i] = static_cast<int64_t>(buf.size());
    }
  };
  std::vector<std::thread> threads;
  for (int t = 1; t < nthreads; ++t) threads.emplace_back(work, t);
  work(0);
  for (auto& th : threads) th.join();
  for (int rc : rcs)
    if (rc != 0) return rc;
  return 0;
}

}  // extern "C"
