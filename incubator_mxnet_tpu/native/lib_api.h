/* Custom-op shared-library ABI (reference: include/mxnet/lib_api.h,
 * the 1.7 external-op loader used by MXLoadLib / python/mxnet/library.py).
 *
 * TPU-native re-design: instead of the reference's operator-registry
 * struct protocol (which plugs kernels into the engine), a library
 * exports a flat, versioned C surface of host-side float32 kernels.
 * The Python loader (incubator_mxnet_tpu/library.py) wraps each op in
 * jax.pure_callback, so loaded ops compose with jit/vmap-of-callback
 * like any other host op while the rest of the program stays on the
 * accelerator.
 *
 * A library implements:
 *   int         mxtpu_lib_api_version(void);      // MXTPU_LIB_API_VERSION
 *   int         mxtpu_lib_num_ops(void);
 *   const char* mxtpu_lib_op_name(int idx);
 *   int         mxtpu_lib_op_infer_shape(...);    // -> out ndim, <0 error
 *   int         mxtpu_lib_op_compute(...);        // -> 0 ok, <0 error
 *
 * All tensors are dense float32, max MXTPU_LIB_MAX_NDIM dims, one
 * output per op.  Thread safety: compute may be called concurrently.
 */
#ifndef MXTPU_LIB_API_H_
#define MXTPU_LIB_API_H_

#include <stdint.h>

#define MXTPU_LIB_API_VERSION 1
#define MXTPU_LIB_MAX_NDIM 8

#ifdef __cplusplus
extern "C" {
#endif

/* ABI version of the library; the loader refuses a mismatch. */
int mxtpu_lib_api_version(void);

/* Number of ops exported. */
int mxtpu_lib_num_ops(void);

/* Name of op `idx` (0 <= idx < mxtpu_lib_num_ops()). */
const char* mxtpu_lib_op_name(int idx);

/* Output shape of `op` for the given input shapes.
 * shapes[i][0..ndims[i]-1] are input i's dims.  Writes up to
 * MXTPU_LIB_MAX_NDIM dims into out_shape, returns the output ndim,
 * or a negative error code. */
int mxtpu_lib_op_infer_shape(const char* op, int n_in,
                             const int64_t* const* shapes,
                             const int* ndims, int64_t* out_shape);

/* Run `op`: inputs are dense float32 buffers with the given shapes;
 * output buffer is pre-allocated to the inferred shape.  Returns 0 on
 * success, negative on error. */
int mxtpu_lib_op_compute(const char* op, int n_in,
                         const float* const* inputs,
                         const int64_t* const* shapes, const int* ndims,
                         float* output, const int64_t* out_shape,
                         int out_ndim);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_LIB_API_H_ */
