/* C predict API — the flat ABI C/C++ applications link against to run a
 * trained checkpoint (reference: include/mxnet/c_predict_api.h; this
 * header matches the reference signatures for the implemented subset).
 *
 * Usage sketch (error handling elided; every function returns 0 on
 * success, -1 with MXGetLastError() set otherwise):
 *
 *   PredictorHandle h;
 *   const char* keys[] = {"data"};
 *   mx_uint indptr[] = {0, 2};
 *   mx_uint shape[] = {1, 4};
 *   MXPredCreate(symbol_json, param_bytes, param_size, 1, 0,
 *                1, keys, indptr, shape, &h);
 *   MXPredSetInput(h, "data", x, 4);
 *   MXPredForward(h);
 *   mx_uint *oshape, ondim;
 *   MXPredGetOutputShape(h, 0, &oshape, &ondim);
 *   MXPredGetOutput(h, 0, out, n);
 *   MXPredFree(h);
 */
#ifndef INCUBATOR_MXNET_TPU_C_PREDICT_API_H_
#define INCUBATOR_MXNET_TPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef uint32_t mx_uint;
typedef void* PredictorHandle;
typedef void* NDListHandle;

/* Last error message of the calling thread (empty string if none). */
const char* MXGetLastError(void);

/* Create a predictor from an nnvm -symbol.json string and the raw bytes
 * of a .params checkpoint (arg:/aux: key convention).
 * dev_type: 1 = cpu, 2 = accelerator; dev_id: ordinal.
 * Input shapes arrive CSR-style: input_shape_indptr has
 * num_input_nodes+1 entries delimiting each input's dims in
 * input_shape_data. */
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);

/* Copy `size` float32 values into the named input. */
int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size);

/* Run the forward pass on the current inputs. */
int MXPredForward(PredictorHandle handle);

/* Shape of output `index`; the returned pointer stays valid until the
 * next MXPredGetOutputShape call on the same handle (or MXPredFree). */
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);

/* Copy output `index` into `data` (`size` = element count, must match
 * the output exactly). */
int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size);

/* Release the predictor. */
int MXPredFree(PredictorHandle handle);

/* Like MXPredCreate, but predict INTERNAL outputs: output_keys names
 * graph nodes ("fc" or "fc_output") whose values become the predictor's
 * outputs — the feature-extraction entry point. */
int MXPredCreatePartialOut(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes,
                           const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           mx_uint num_output_nodes,
                           const char** output_keys,
                           PredictorHandle* out);

/* New predictor over the SAME weights with new input shapes (batch or
 * sequence-length change without re-decoding the checkpoint).  The old
 * handle stays valid; free both. */
int MXPredReshape(mx_uint num_input_nodes, const char** input_keys,
                  const mx_uint* input_shape_indptr,
                  const mx_uint* input_shape_data,
                  PredictorHandle handle, PredictorHandle* out);

/* Partial forward (reference: step through the graph for debugging).
 * The executor here is ONE compiled XLA program — there is no node-level
 * stepping to expose — so step 0 runs the whole forward and *step_left
 * is always 0; step > 0 is an error. */
int MXPredPartialForward(PredictorHandle handle, int step,
                         int* step_left);

/* num_threads predictors over ONE decoded checkpoint, for one C host
 * thread each.  CONCURRENCY CONTRACT: each handle owns its executor and
 * the compiled XLA computation runs outside the GIL, but every entry
 * point marshals through the embedded interpreter, so ABI calls from
 * different threads serialize on the GIL for the marshaling portion.
 * out must have room for num_threads handles. */
int MXPredCreateMultiThread(const char* symbol_json_str,
                            const void* param_bytes, int param_size,
                            int dev_type, int dev_id,
                            mx_uint num_input_nodes,
                            const char** input_keys,
                            const mx_uint* input_shape_indptr,
                            const mx_uint* input_shape_data,
                            int num_threads, PredictorHandle* out);

/* Decode a .nd file's bytes (the mean-image convention): a list of
 * arrays, optionally keyed.  All arrays are exported as float32. */
int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                   NDListHandle* out, mx_uint* out_length);

/* Borrowed views of entry `index`; pointers stay valid until
 * MXNDListFree.  Bare (unkeyed) lists return "" keys. */
int MXNDListGet(NDListHandle handle, mx_uint index, const char** out_key,
                const float** out_data, const mx_uint** out_shape,
                mx_uint* out_ndim);

int MXNDListFree(NDListHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* INCUBATOR_MXNET_TPU_C_PREDICT_API_H_ */
