// Example custom-op library (reference analog:
// example/extensions/lib_custom_op/gemm_lib.cc — the 1.7 loadable-op
// sample).  Exports two ops over the mxtpu lib ABI:
//   my_gemm(a, b)  — (M,K) x (K,N) -> (M,N) matmul
//   my_relu6(x)    — min(max(x, 0), 6) elementwise
//
// Build:  g++ -O2 -shared -fPIC -o libcustom_ops.so example_custom_ops.cc
#include <algorithm>
#include <cstring>

#include "lib_api.h"

namespace {

int64_t numel(const int64_t* shape, int ndim) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

}  // namespace

extern "C" {

int mxtpu_lib_api_version(void) { return MXTPU_LIB_API_VERSION; }

int mxtpu_lib_num_ops(void) { return 2; }

const char* mxtpu_lib_op_name(int idx) {
  switch (idx) {
    case 0: return "my_gemm";
    case 1: return "my_relu6";
    default: return nullptr;
  }
}

int mxtpu_lib_op_infer_shape(const char* op, int n_in,
                             const int64_t* const* shapes,
                             const int* ndims, int64_t* out_shape) {
  if (std::strcmp(op, "my_gemm") == 0) {
    if (n_in != 2 || ndims[0] != 2 || ndims[1] != 2) return -2;
    if (shapes[0][1] != shapes[1][0]) return -3;
    out_shape[0] = shapes[0][0];
    out_shape[1] = shapes[1][1];
    return 2;
  }
  if (std::strcmp(op, "my_relu6") == 0) {
    if (n_in != 1) return -2;
    for (int i = 0; i < ndims[0]; ++i) out_shape[i] = shapes[0][i];
    return ndims[0];
  }
  return -1;
}

int mxtpu_lib_op_compute(const char* op, int n_in,
                         const float* const* inputs,
                         const int64_t* const* shapes, const int* ndims,
                         float* output, const int64_t* out_shape,
                         int out_ndim) {
  if (std::strcmp(op, "my_gemm") == 0) {
    const int64_t M = shapes[0][0], K = shapes[0][1], N = shapes[1][1];
    const float* a = inputs[0];
    const float* b = inputs[1];
    for (int64_t i = 0; i < M; ++i) {
      for (int64_t j = 0; j < N; ++j) {
        float acc = 0.f;
        for (int64_t k = 0; k < K; ++k) acc += a[i * K + k] * b[k * N + j];
        output[i * N + j] = acc;
      }
    }
    return 0;
  }
  if (std::strcmp(op, "my_relu6") == 0) {
    const int64_t n = numel(shapes[0], ndims[0]);
    for (int64_t i = 0; i < n; ++i)
      output[i] = std::min(std::max(inputs[0][i], 0.f), 6.f);
    return 0;
  }
  return -1;
}

}  // extern "C"
