"""Native runtime components, built on demand (reference analog: the C++
core under src/ + 3rdparty/dmlc-core; SURVEY's "native where the
reference's is" mandate).

The shared object is compiled from the in-tree C++ source with the system
toolchain the first time it is needed (and recompiled when the source is
newer), cached next to the source.  Loading is best-effort: when a
compiler is unavailable the callers fall back to their pure-Python paths,
so the framework never hard-requires the native build.

Bindings are ctypes over a C ABI (pybind11 is deliberately not used — it
is not in the image, and a flat ABI keeps the boundary auditable, like
the reference's own C API layer, include/mxnet/c_api.h).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "recordio_core.cc")
_SO = os.path.join(_DIR, "_recordio_core.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _compile(src, so, extra_flags=(), timeout=180) -> bool:
    """Compile ``src`` into shared object ``so``: per-pid temp path, then
    atomic rename — concurrent processes (launch.py workers) each build
    their own copy and rename races are last-writer-wins on a COMPLETE
    binary."""
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
           + [f for f in extra_flags if f.startswith("-I")]
           + ["-o", tmp, src]
           + [f for f in extra_flags if not f.startswith("-I")])
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode == 0 and os.path.isfile(tmp):
            os.replace(tmp, so)
            return True
        return False
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _needs_build(so, src) -> bool:
    """True when the .so must be (re)built; False when an up-to-date .so
    exists OR only the .so exists (source stripped in deployment — use
    the prebuilt binary rather than failing)."""
    if not os.path.isfile(so):
        return True
    if not os.path.isfile(src):
        return False
    return os.path.getmtime(so) < os.path.getmtime(src)


def _build() -> bool:
    return _compile(_SRC, _SO)


def load():
    """The recordio core library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _needs_build(_SO, _SRC) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.rio_open.restype = ctypes.c_void_p
        lib.rio_open.argtypes = [ctypes.c_char_p]
        lib.rio_close.argtypes = [ctypes.c_void_p]
        lib.rio_free.argtypes = [ctypes.c_void_p]
        lib.rio_read_at.restype = ctypes.c_int
        lib.rio_read_at.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64)]
        lib.rio_scan_index.restype = ctypes.c_int64
        lib.rio_scan_index.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
        lib.rio_read_many.restype = ctypes.c_int
        lib.rio_read_many.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


class NativeRecordReader:
    """Positional RecordIO reader over the C core: thread-safe (pread —
    no shared cursor), with a parallel batched read.  Raises OSError if
    the native core is unavailable — callers decide the fallback."""

    def __init__(self, path: str):
        lib = load()
        if lib is None:
            raise OSError("native recordio core unavailable")
        self._lib = lib
        self._h = lib.rio_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open {path}")
        self.path = path

    def close(self):
        if getattr(self, "_h", None):
            self._lib.rio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def read_at(self, offset: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.rio_read_at(self._h, offset, ctypes.byref(out),
                                   ctypes.byref(n))
        if rc != 0:
            raise IOError(f"recordio read error {rc} at {offset} "
                          f"in {self.path}")
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._lib.rio_free(out)

    def read_many(self, offsets, nthreads: int = 4):
        n = len(offsets)
        if n == 0:
            return []
        offs = (ctypes.c_int64 * n)(*offsets)
        bufs = (ctypes.POINTER(ctypes.c_uint8) * n)()
        lens = (ctypes.c_int64 * n)()
        rc = self._lib.rio_read_many(self._h, offs, n, int(nthreads),
                                     bufs, lens)
        out = []
        try:
            for i in range(n):
                out.append(ctypes.string_at(bufs[i], lens[i])
                           if bufs[i] else None)
        finally:
            for i in range(n):
                if bufs[i]:
                    self._lib.rio_free(bufs[i])
        if rc != 0:
            raise IOError(f"recordio batched read error {rc} "
                          f"in {self.path}")
        return out


_PREDICT_SRC = os.path.join(_DIR, "c_predict_api.cc")
_PREDICT_SO = os.path.join(_DIR, "_c_predict_api.so")


def _python_embed_flags():
    """Compiler/linker flags to embed THIS interpreter (what
    `python3-config --includes --embed --ldflags` prints, resolved via
    sysconfig so the right Python is always used)."""
    import sysconfig
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    cflags = [f"-I{inc}"]
    ldflags = [f"-L{libdir}", f"-Wl,-rpath,{libdir}", f"-lpython{ver}"]
    return cflags, ldflags


def build_predict_api():
    """Build the C predict ABI shared object (c_predict_api.cc) if
    needed; returns its path, or None when the toolchain/embed libs are
    unavailable (callers and tests skip with that reason)."""
    if not _needs_build(_PREDICT_SO, _PREDICT_SRC):
        return _PREDICT_SO
    try:
        cflags, ldflags = _python_embed_flags()
    except Exception:
        return None
    if _compile(_PREDICT_SRC, _PREDICT_SO,
                extra_flags=cflags + ldflags, timeout=300):
        return _PREDICT_SO
    return None


def scan_index(path: str):
    """Logical-record start offsets via the C core, or None when the
    native build is unavailable (caller falls back to the Python scan)."""
    lib = load()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_int64)()
    n = lib.rio_scan_index(path.encode(), ctypes.byref(out))
    if n < 0:
        return None
    try:
        return [out[i] for i in range(n)]
    finally:
        lib.rio_free(out)
