"""Native runtime components, built on demand (reference analog: the C++
core under src/ + 3rdparty/dmlc-core; SURVEY's "native where the
reference's is" mandate).

The shared object is compiled from the in-tree C++ source with the system
toolchain the first time it is needed (and recompiled when the source is
newer), cached next to the source.  Loading is best-effort: when a
compiler is unavailable the callers fall back to their pure-Python paths,
so the framework never hard-requires the native build.

Bindings are ctypes over a C ABI (pybind11 is deliberately not used — it
is not in the image, and a flat ABI keeps the boundary auditable, like
the reference's own C API layer, include/mxnet/c_api.h).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "recordio_core.cc")
_SO = os.path.join(_DIR, "_recordio_core.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    # compile to a per-pid temp path, then atomic-rename into place:
    # concurrent processes (launch.py workers) each build their own copy
    # and the rename races are last-writer-wins on a COMPLETE binary
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-o", tmp, _SRC]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=180)
        if out.returncode == 0 and os.path.isfile(tmp):
            os.replace(tmp, _SO)
            return True
        return False
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def load():
    """The recordio core library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        need_build = (not os.path.isfile(_SO)
                      or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if need_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.rio_open.restype = ctypes.c_void_p
        lib.rio_open.argtypes = [ctypes.c_char_p]
        lib.rio_close.argtypes = [ctypes.c_void_p]
        lib.rio_free.argtypes = [ctypes.c_void_p]
        lib.rio_read_at.restype = ctypes.c_int
        lib.rio_read_at.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64)]
        lib.rio_scan_index.restype = ctypes.c_int64
        lib.rio_scan_index.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
        lib.rio_read_many.restype = ctypes.c_int
        lib.rio_read_many.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


class NativeRecordReader:
    """Positional RecordIO reader over the C core: thread-safe (pread —
    no shared cursor), with a parallel batched read.  Raises OSError if
    the native core is unavailable — callers decide the fallback."""

    def __init__(self, path: str):
        lib = load()
        if lib is None:
            raise OSError("native recordio core unavailable")
        self._lib = lib
        self._h = lib.rio_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open {path}")
        self.path = path

    def close(self):
        if getattr(self, "_h", None):
            self._lib.rio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def read_at(self, offset: int) -> bytes:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int64()
        rc = self._lib.rio_read_at(self._h, offset, ctypes.byref(out),
                                   ctypes.byref(n))
        if rc != 0:
            raise IOError(f"recordio read error {rc} at {offset} "
                          f"in {self.path}")
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._lib.rio_free(out)

    def read_many(self, offsets, nthreads: int = 4):
        n = len(offsets)
        if n == 0:
            return []
        offs = (ctypes.c_int64 * n)(*offsets)
        bufs = (ctypes.POINTER(ctypes.c_uint8) * n)()
        lens = (ctypes.c_int64 * n)()
        rc = self._lib.rio_read_many(self._h, offs, n, int(nthreads),
                                     bufs, lens)
        out = []
        try:
            for i in range(n):
                out.append(ctypes.string_at(bufs[i], lens[i])
                           if bufs[i] else None)
        finally:
            for i in range(n):
                if bufs[i]:
                    self._lib.rio_free(bufs[i])
        if rc != 0:
            raise IOError(f"recordio batched read error {rc} "
                          f"in {self.path}")
        return out


def scan_index(path: str):
    """Logical-record start offsets via the C core, or None when the
    native build is unavailable (caller falls back to the Python scan)."""
    lib = load()
    if lib is None:
        return None
    out = ctypes.POINTER(ctypes.c_int64)()
    n = lib.rio_scan_index(path.encode(), ctypes.byref(out))
    if n < 0:
        return None
    try:
        return [out[i] for i in range(n)]
    finally:
        lib.rio_free(out)
