/* Pure-C consumer of the predict ABI (reference analog:
 * example/image-classification/predict-cpp/ — the deployment demo).
 * Loads a -symbol.json + .params checkpoint from argv, runs one forward
 * on a fixed input, prints "shape d0 d1 ..." then the output floats —
 * no Python anywhere in THIS translation unit; the interpreter is an
 * implementation detail behind the ABI.
 *
 * Built and executed by tests/test_c_predict_api.py. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "c_predict_api.h"

static char* read_file(const char* path, long* size_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  char* buf = (char*)malloc((size_t)n + 1);
  if (fread(buf, 1, (size_t)n, f) != (size_t)n) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[n] = '\0';
  fclose(f);
  *size_out = n;
  return buf;
}

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s model-symbol.json model.params\n", argv[0]);
    return 2;
  }
  long json_size = 0, param_size = 0;
  char* json = read_file(argv[1], &json_size);
  char* params = read_file(argv[2], &param_size);
  if (!json || !params) {
    fprintf(stderr, "cannot read model files\n");
    return 2;
  }

  PredictorHandle h = NULL;
  const char* keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {2, 4};
  if (MXPredCreate(json, params, (int)param_size, /*cpu*/ 1, 0, 1, keys,
                   indptr, shape, &h) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return 1;
  }

  float x[8] = {1.0f, 2.0f, 3.0f, 4.0f, -1.0f, 0.5f, 0.0f, 2.0f};
  if (MXPredSetInput(h, "data", x, 8) != 0 || MXPredForward(h) != 0) {
    fprintf(stderr, "forward: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint* oshape = NULL;
  mx_uint ondim = 0;
  if (MXPredGetOutputShape(h, 0, &oshape, &ondim) != 0) {
    fprintf(stderr, "shape: %s\n", MXGetLastError());
    return 1;
  }
  printf("shape");
  mx_uint total = 1;
  for (mx_uint i = 0; i < ondim; ++i) {
    printf(" %u", oshape[i]);
    total *= oshape[i];
  }
  printf("\n");

  float* out = (float*)malloc(sizeof(float) * total);
  if (MXPredGetOutput(h, 0, out, total) != 0) {
    fprintf(stderr, "output: %s\n", MXGetLastError());
    return 1;
  }
  for (mx_uint i = 0; i < total; ++i) {
    printf("%.6f%c", (double)out[i], i + 1 == total ? '\n' : ' ');
  }

  free(out);
  free(json);
  free(params);
  MXPredFree(h);
  return 0;
}
