"""Python half of the C predict API (reference:
include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc — the
deployment surface C/C++ applications link against).

The native layer (``c_predict_api.cc``) embeds CPython and calls the
functions here; this module owns everything above the marshaling line:
parse the nnvm -symbol.json, decode the ``arg:``/``aux:`` ``.params``
bytes, run forwards.  The compute path is the serving subsystem's
:class:`~incubator_mxnet_tpu.serving.InferenceEngine` in exact-shape
mode: one engine per (inputs, outputs) selection is SHARED through the
``_shared`` handle that ``MXPredReshape`` / ``MXPredCreateMultiThread``
pass around, so every handle over the same checkpoint rides one
per-shape compiled-program cache — a ``reshape`` to a previously seen
shape dispatches a warm program instead of re-tracing.  The C caller
gets the same compiled program a Python caller would, which is the
TPU-native answer to the reference's C++ engine behind its predict
API."""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as _np


def _pin_device(dev_type: int) -> None:
    """dev_type follows the reference enum: 1 = cpu, 2 = gpu (here: the
    accelerator).  cpu pins the jax platform BEFORE the framework import
    so a deployment box never touches (or hangs on) an accelerator
    runtime it doesn't want."""
    if dev_type == 1:
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            # backend already initialized; if it settled on an
            # accelerator, a cpu-ctx predictor would silently compute
            # there (ops follow input placement) — surface it
            if jax.default_backend() != "cpu":
                import warnings
                warnings.warn(
                    "predictor requested dev_type=cpu but the jax "
                    f"backend is already {jax.default_backend()!r}; "
                    "cpu placement rides the ctx device, but create "
                    "the predictor before any accelerator use to pin "
                    "the platform", stacklevel=3)


class Predictor:
    def __init__(self, symbol_json: str, param_bytes: bytes,
                 dev_type: int, dev_id: int,
                 inputs: Sequence[Tuple[str, Tuple[int, ...]]],
                 output_names: Sequence[str] = (),
                 _shared=None):
        """``output_names`` selects INTERNAL outputs by name (the
        reference's MXPredCreatePartialOut contract, e.g. "fc_output" or
        "fc"); empty means the symbol's own outputs.  ``_shared`` is the
        (sym, arg_params, aux_params, engines) bundle an existing
        predictor hands to MXPredReshape/MXPredCreateMultiThread so the
        checkpoint is decoded — and each (inputs, outputs) selection
        compiled — once per process, not once per handle."""
        _pin_device(dev_type)
        import incubator_mxnet_tpu as mx
        from incubator_mxnet_tpu.symbol import symbol as sym_mod
        from incubator_mxnet_tpu.serving import InferenceEngine

        self._mx = mx
        if _shared is not None:
            sym, arg_params, aux_params = _shared[:3]
            engines = _shared[3] if len(_shared) > 3 else {}
        else:
            from incubator_mxnet_tpu.ndarray.utils import load_frombuffer
            sym = sym_mod.load_json(symbol_json)
            loaded = load_frombuffer(param_bytes)
            if not isinstance(loaded, dict):
                raise ValueError(".params bytes hold a bare list, not "
                                 "the arg:/aux: dict a checkpoint "
                                 "carries")
            arg_params = {k[4:]: v for k, v in loaded.items()
                          if k.startswith("arg:")}
            aux_params = {k[4:]: v for k, v in loaded.items()
                          if k.startswith("aux:")}
            engines = {}
        self._shared = (sym, arg_params, aux_params, engines)
        self._dev = (dev_type, dev_id)
        ctx = mx.cpu(dev_id) if dev_type == 1 else mx.tpu(dev_id)

        self._input_names = [k for k, _ in inputs]
        self._input_shapes = {k: tuple(s) for k, s in inputs}
        self._output_names = list(output_names)
        key = (tuple(self._input_names),
               tuple(str(n) for n in output_names), self._dev)
        engine = engines.get(key)
        if engine is None:
            # exact-shape mode: the jit cache keys on input shapes, one
            # compiled program per shape set, shared by every handle
            engine = InferenceEngine.from_symbol(
                sym, arg_params, aux_params, self._input_names,
                output_names=[str(n) for n in output_names],
                name="predict:" + (getattr(sym, "name", None) or "net"),
                ctx=ctx)
            engines[key] = engine
        self._engine = engine
        self._inputs: Dict[str, _np.ndarray] = {
            name: _np.zeros(shape, dtype=_np.float32)
            for name, shape in inputs}
        self._pending: Dict[str, object] = {}
        self._outputs: List[_np.ndarray] = []
        self.forward()        # reference semantics: predictor is runnable
        #                       (and output shapes queryable) on create

    def reshape(self, inputs) -> "Predictor":
        """New predictor over the SAME weights with new input shapes
        (reference: MXPredReshape).  The old handle stays valid."""
        return Predictor("", b"", self._dev[0], self._dev[1],
                         _norm_inputs(inputs),
                         output_names=self._output_names,
                         _shared=self._shared)

    def set_input(self, key: str, data: bytes) -> None:
        if key not in self._input_names:
            raise ValueError(f"unknown input {key!r}; declared inputs: "
                             f"{self._input_names}")
        arr = _np.frombuffer(data, dtype=_np.float32).reshape(
            self._input_shapes[key])
        self._pending[key] = arr

    def forward(self) -> None:
        self._inputs.update(self._pending)
        self._pending = {}
        outs = self._engine.run_exact(
            [self._inputs[n] for n in self._input_names])
        self._outputs = [_np.ascontiguousarray(
            _np.asarray(o).astype(_np.float32)) for o in outs]

    def num_outputs(self) -> int:
        return len(self._outputs)

    def get_output_shape(self, index: int) -> Tuple[int, ...]:
        return tuple(int(d) for d in self._outputs[index].shape)

    def get_output(self, index: int) -> bytes:
        return self._outputs[index].tobytes()


class NDList:
    """Decoded .nd file (reference: MXNDListCreate — the mean-image /
    aux-blob loader of the predict ABI).  Bare lists get empty keys,
    dicts keep their save() keys; every array is exported float32."""

    def __init__(self, raw: bytes):
        from incubator_mxnet_tpu.ndarray.utils import load_frombuffer
        loaded = load_frombuffer(raw)
        if isinstance(loaded, dict):
            items = list(loaded.items())
        else:
            items = [("", a) for a in loaded]
        self._keys = [str(k) for k, _ in items]
        self._arrays = [_np.ascontiguousarray(
            a.asnumpy().astype(_np.float32)) for _, a in items]

    def __len__(self) -> int:
        return len(self._keys)

    def key(self, index: int) -> str:
        return self._keys[index]

    def shape(self, index: int) -> Tuple[int, ...]:
        return tuple(int(d) for d in self._arrays[index].shape)

    def data(self, index: int) -> bytes:
        return self._arrays[index].tobytes()


def _norm_inputs(inputs):
    return [(str(k), tuple(int(d) for d in s)) for k, s in inputs]


def create(symbol_json: str, param_bytes: bytes, dev_type: int,
           dev_id: int, inputs, output_names=()) -> Predictor:
    return Predictor(symbol_json, param_bytes, dev_type, dev_id,
                     _norm_inputs(inputs),
                     output_names=[str(n) for n in output_names])


def create_multi_thread(symbol_json: str, param_bytes: bytes,
                        dev_type: int, dev_id: int, inputs,
                        num_threads: int):
    """N predictors over ONE decoded checkpoint (reference:
    MXPredCreateMultiThread).  Each handle owns its executor, so C host
    threads can drive them concurrently; entry into the embedded
    interpreter still serializes on the GIL (documented in the
    header) — the compiled XLA computation itself runs outside it."""
    first = Predictor(symbol_json, param_bytes, dev_type, dev_id,
                      _norm_inputs(inputs))
    rest = [first.reshape(inputs) for _ in range(int(num_threads) - 1)]
    return [first] + rest


def ndlist_create(raw: bytes) -> NDList:
    return NDList(raw)
