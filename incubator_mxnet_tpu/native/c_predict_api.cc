// C predict API (reference: include/mxnet/c_predict_api.h +
// src/c_api/c_predict_api.cc — the flat ABI C/C++ applications link
// against to run a trained checkpoint without any Python on THEIR side).
//
// TPU-native re-design: the reference backs this ABI with its C++ graph
// executor; here the executor IS a jit-compiled XLA program, so the
// native layer embeds CPython and drives the same
// incubator_mxnet_tpu executor a Python caller would get — the C caller
// still sees only this ABI (handles + float buffers + MXGetLastError),
// and the heavy lifting stays in the compiled XLA program.
//
// ABI implemented (signatures match the reference):
//   MXGetLastError, MXPredCreate, MXPredCreatePartialOut, MXPredReshape,
//   MXPredSetInput, MXPredForward, MXPredPartialForward,
//   MXPredGetOutputShape, MXPredGetOutput, MXPredFree,
//   MXPredCreateMultiThread (GIL contract documented in the header),
//   MXNDListCreate, MXNDListGet, MXNDListFree
//
// Build (the test does this; python3-config supplies the embed flags):
//   g++ -O2 -shared -fPIC -std=c++17 c_predict_api.cc \
//       $(python3-config --includes) $(python3-config --embed --ldflags) \
//       -o _c_predict_api.so
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

// the public header declares every extern-C signature below, so a
// drifting declaration becomes a compile error here, not a consumer's
// stack corruption at runtime
#include "c_predict_api.h"

namespace {

thread_local std::string g_last_error;

struct Predictor {
  PyObject* obj = nullptr;                  // predict_bridge.Predictor
  std::vector<mx_uint> shape_buf;           // owns MXPredGetOutputShape
};

// Initialize an interpreter if the host process doesn't have one (a pure
// C caller); release the GIL afterwards so every entry point can use the
// PyGILState API uniformly.  call_once: concurrent first MXPredCreate
// calls from a multithreaded C host must not race Py_InitializeEx.
std::once_flag g_py_init_once;
bool g_py_init_ok = false;

bool ensure_python() {
  std::call_once(g_py_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      if (!Py_IsInitialized()) return;
      PyEval_SaveThread();
    }
    g_py_init_ok = true;
  });
  if (!g_py_init_ok) {
    g_last_error = "embedded Python interpreter failed to initialize";
  }
  return g_py_init_ok;
}

// capture the current Python exception into g_last_error
void take_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_last_error = std::string(where) + ": ";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      g_last_error += c != nullptr ? c : "<unprintable>";
      Py_DECREF(s);
    }
  } else {
    g_last_error += "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  // PyObject_Str/PyUnicode_AsUTF8 may themselves have raised; the next
  // CPython call on this thread must start exception-clean
  PyErr_Clear();
}

PyObject* bridge() {
  // imported once per process; returns a borrowed-module new reference
  return PyImport_ImportModule(
      "incubator_mxnet_tpu.native.predict_bridge");
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// [(key, (d0, d1, ...)), ...] from the CSR-style shape triplet.
// Returns a new reference, or nullptr with a Python error set (every
// inner allocation checked: the ABI's contract is rc=-1 +
// MXGetLastError, never a segfault in the host process).
PyObject* build_inputs_list(mx_uint num_input_nodes,
                            const char** input_keys,
                            const mx_uint* input_shape_indptr,
                            const mx_uint* input_shape_data) {
  PyObject* inputs = PyList_New(num_input_nodes);
  if (inputs == nullptr) return nullptr;
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    const mx_uint begin = input_shape_indptr[i];
    const mx_uint end = input_shape_indptr[i + 1];
    PyObject* shape = PyTuple_New(end - begin);
    if (shape == nullptr) { Py_DECREF(inputs); return nullptr; }
    for (mx_uint d = begin; d < end; ++d) {
      PyObject* dim = PyLong_FromUnsignedLong(input_shape_data[d]);
      if (dim == nullptr) {
        Py_DECREF(shape);
        Py_DECREF(inputs);
        return nullptr;
      }
      PyTuple_SET_ITEM(shape, d - begin, dim);
    }
    PyObject* key = PyUnicode_FromString(input_keys[i]);
    PyObject* pair = key != nullptr ? PyTuple_New(2) : nullptr;
    if (pair == nullptr) {
      Py_XDECREF(key);
      Py_DECREF(shape);
      Py_DECREF(inputs);
      return nullptr;
    }
    PyTuple_SET_ITEM(pair, 0, key);
    PyTuple_SET_ITEM(pair, 1, shape);
    PyList_SET_ITEM(inputs, i, pair);
  }
  return inputs;
}

// Decoded .nd file, copied into C++-owned storage at create so
// MXNDListGet never needs the GIL and pointers stay stable.
struct NDList {
  std::vector<std::string> keys;
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<std::vector<float>> data;
};

// Shared creator scaffold (MXPredCreate / CreatePartialOut /
// CreateMultiThread differ only in one trailing argument): init the
// interpreter, marshal (inputs[, outputs], params), call `method` on
// the bridge, and return the new-reference result — or nullptr with
// g_last_error set.  Refcount-sensitive code lives HERE once.
PyObject* call_create(const char* who, const char* method,
                      const char* symbol_json, const void* param_bytes,
                      int param_size, int dev_type, int dev_id,
                      mx_uint n_in, const char** in_keys,
                      const mx_uint* indptr, const mx_uint* shp,
                      mx_uint n_out, const char** out_keys,
                      int num_threads) {
  if (!ensure_python()) return nullptr;
  Gil gil;
  PyObject* mod = bridge();
  if (mod == nullptr) {
    take_py_error(who);
    return nullptr;
  }
  PyObject* inputs = build_inputs_list(n_in, in_keys, indptr, shp);
  bool ok = inputs != nullptr;
  PyObject* outputs = nullptr;
  if (ok && out_keys != nullptr) {
    outputs = PyList_New(n_out);
    ok = outputs != nullptr;
    for (mx_uint i = 0; ok && i < n_out; ++i) {
      PyObject* name = PyUnicode_FromString(out_keys[i]);
      ok = name != nullptr;
      if (ok) PyList_SET_ITEM(outputs, i, name);
    }
  }
  PyObject* params =
      ok ? PyBytes_FromStringAndSize(
               static_cast<const char*>(param_bytes), param_size)
         : nullptr;
  PyObject* res = nullptr;
  if (params != nullptr) {
    if (out_keys != nullptr) {
      res = PyObject_CallMethod(mod, method, "sOiiOO", symbol_json,
                                params, dev_type, dev_id, inputs,
                                outputs);
    } else if (num_threads >= 1) {
      res = PyObject_CallMethod(mod, method, "sOiiOi", symbol_json,
                                params, dev_type, dev_id, inputs,
                                num_threads);
    } else {
      res = PyObject_CallMethod(mod, method, "sOiiO", symbol_json,
                                params, dev_type, dev_id, inputs);
    }
  }
  Py_XDECREF(params);
  Py_XDECREF(outputs);
  Py_XDECREF(inputs);
  Py_DECREF(mod);
  if (res == nullptr) take_py_error(who);
  return res;
}

}  // namespace

extern "C" {

// (MXPredForward / MXPredFree used below are declared by the included
// public header — no in-file re-declaration, one signature source)

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out) {
  if (out == nullptr || symbol_json_str == nullptr) {
    g_last_error = "MXPredCreate: null argument";
    return -1;
  }
  PyObject* res = call_create(
      "MXPredCreate", "create", symbol_json_str, param_bytes,
      param_size, dev_type, dev_id, num_input_nodes, input_keys,
      input_shape_indptr, input_shape_data, 0, nullptr, 0);
  if (res == nullptr) return -1;
  auto* pred = new Predictor();
  pred->obj = res;
  *out = pred;
  return 0;
}

int MXPredCreatePartialOut(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes,
                           const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           mx_uint num_output_nodes,
                           const char** output_keys,
                           PredictorHandle* out) {
  if (out == nullptr || symbol_json_str == nullptr) {
    g_last_error = "MXPredCreatePartialOut: null argument";
    return -1;
  }
  PyObject* res = call_create(
      "MXPredCreatePartialOut", "create", symbol_json_str, param_bytes,
      param_size, dev_type, dev_id, num_input_nodes, input_keys,
      input_shape_indptr, input_shape_data, num_output_nodes,
      output_keys, 0);
  if (res == nullptr) return -1;
  auto* pred = new Predictor();
  pred->obj = res;
  *out = pred;
  return 0;
}

int MXPredReshape(mx_uint num_input_nodes, const char** input_keys,
                  const mx_uint* input_shape_indptr,
                  const mx_uint* input_shape_data,
                  PredictorHandle handle, PredictorHandle* out) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || out == nullptr) {
    g_last_error = "MXPredReshape: null argument";
    return -1;
  }
  Gil gil;
  PyObject* inputs = build_inputs_list(num_input_nodes, input_keys,
                                       input_shape_indptr,
                                       input_shape_data);
  PyObject* res =
      PyObject_CallMethod(pred->obj, "reshape", "O", inputs);
  Py_XDECREF(inputs);
  if (res == nullptr) {
    take_py_error("MXPredReshape");
    return -1;
  }
  auto* fresh = new Predictor();
  fresh->obj = res;
  *out = fresh;
  return 0;
}

int MXPredPartialForward(PredictorHandle handle, int step,
                         int* step_left) {
  if (step != 0) {
    // one compiled XLA program — no node-level stepping to expose
    g_last_error = "MXPredPartialForward: the executor is a single "
                   "compiled XLA program; only step 0 (full forward) "
                   "exists";
    return -1;
  }
  const int rc = MXPredForward(handle);
  if (rc == 0 && step_left != nullptr) *step_left = 0;
  return rc;
}

int MXPredCreateMultiThread(const char* symbol_json_str,
                            const void* param_bytes, int param_size,
                            int dev_type, int dev_id,
                            mx_uint num_input_nodes,
                            const char** input_keys,
                            const mx_uint* input_shape_indptr,
                            const mx_uint* input_shape_data,
                            int num_threads, PredictorHandle* out) {
  if (out == nullptr || symbol_json_str == nullptr || num_threads < 1) {
    g_last_error = "MXPredCreateMultiThread: null argument or "
                   "num_threads < 1";
    return -1;
  }
  PyObject* res = call_create(
      "MXPredCreateMultiThread", "create_multi_thread", symbol_json_str,
      param_bytes, param_size, dev_type, dev_id, num_input_nodes,
      input_keys, input_shape_indptr, input_shape_data, 0, nullptr,
      num_threads);
  if (res == nullptr) return -1;
  Gil gil;
  for (int i = 0; i < num_threads; ++i) {
    PyObject* item = PyList_GetItem(res, i);  // borrowed
    if (item == nullptr) {
      take_py_error("MXPredCreateMultiThread: handle list");
      for (int j = 0; j < i; ++j) {
        MXPredFree(out[j]);
        out[j] = nullptr;
      }
      Py_DECREF(res);
      return -1;
    }
    Py_INCREF(item);
    auto* pred = new Predictor();
    pred->obj = item;
    out[i] = pred;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || key == nullptr || data == nullptr) {
    g_last_error = "MXPredSetInput: null argument";
    return -1;
  }
  Gil gil;
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * 4);
  PyObject* res =
      PyObject_CallMethod(pred->obj, "set_input", "sO", key, bytes);
  Py_DECREF(bytes);
  if (res == nullptr) {
    take_py_error("MXPredSetInput");
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr) {
    g_last_error = "MXPredForward: null handle";
    return -1;
  }
  Gil gil;
  PyObject* res = PyObject_CallMethod(pred->obj, "forward", nullptr);
  if (res == nullptr) {
    take_py_error("MXPredForward");
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || shape_data == nullptr || shape_ndim == nullptr) {
    g_last_error = "MXPredGetOutputShape: null argument";
    return -1;
  }
  Gil gil;
  PyObject* res = PyObject_CallMethod(pred->obj, "get_output_shape", "I",
                                      index);
  if (res == nullptr) {
    take_py_error("MXPredGetOutputShape");
    return -1;
  }
  const Py_ssize_t n = PyTuple_Size(res);
  pred->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    const unsigned long v =
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(res, i));
    if (v == static_cast<unsigned long>(-1) && PyErr_Occurred()) {
      Py_DECREF(res);
      take_py_error("MXPredGetOutputShape: non-integer dim");
      return -1;
    }
    pred->shape_buf[static_cast<size_t>(i)] = static_cast<mx_uint>(v);
  }
  Py_DECREF(res);
  *shape_data = pred->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || data == nullptr) {
    g_last_error = "MXPredGetOutput: null argument";
    return -1;
  }
  Gil gil;
  PyObject* res =
      PyObject_CallMethod(pred->obj, "get_output", "I", index);
  if (res == nullptr) {
    take_py_error("MXPredGetOutput");
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &n) != 0) {
    Py_DECREF(res);
    take_py_error("MXPredGetOutput: bytes");
    return -1;
  }
  if (static_cast<Py_ssize_t>(size) * 4 != n) {
    g_last_error = "MXPredGetOutput: buffer size " +
                   std::to_string(size) + " floats != output " +
                   std::to_string(n / 4) + " floats";
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(n));
  Py_DECREF(res);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr) return 0;
  {
    Gil gil;
    Py_XDECREF(pred->obj);
  }
  delete pred;
  return 0;
}

int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                   NDListHandle* out, mx_uint* out_length) {
  if (nd_file_bytes == nullptr || out == nullptr ||
      out_length == nullptr) {
    g_last_error = "MXNDListCreate: null argument";
    return -1;
  }
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mod = bridge();
  if (mod == nullptr) {
    take_py_error("MXNDListCreate: import predict_bridge");
    return -1;
  }
  PyObject* raw = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  PyObject* obj = PyObject_CallMethod(mod, "ndlist_create", "O", raw);
  Py_XDECREF(raw);
  Py_DECREF(mod);
  if (obj == nullptr) {
    take_py_error("MXNDListCreate");
    return -1;
  }
  // copy everything into C++-owned storage: MXNDListGet then needs no
  // GIL and the returned pointers stay stable until MXNDListFree
  auto list = std::make_unique<NDList>();
  const Py_ssize_t n = PyObject_Length(obj);
  bool ok = n >= 0;
  for (Py_ssize_t i = 0; ok && i < n; ++i) {
    PyObject* key = PyObject_CallMethod(obj, "key", "n", i);
    PyObject* shape = PyObject_CallMethod(obj, "shape", "n", i);
    PyObject* data = PyObject_CallMethod(obj, "data", "n", i);
    ok = key != nullptr && shape != nullptr && data != nullptr;
    const char* key_c = ok ? PyUnicode_AsUTF8(key) : nullptr;
    ok = ok && key_c != nullptr;   // surrogate-escaped names decode to
    if (ok) {                      // nullptr: rc=-1, never a segfault
      list->keys.emplace_back(key_c);
      std::vector<mx_uint> dims;
      for (Py_ssize_t d = 0; ok && d < PyTuple_Size(shape); ++d) {
        const unsigned long v =
            PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, d));
        ok = !(v == static_cast<unsigned long>(-1) && PyErr_Occurred());
        dims.push_back(static_cast<mx_uint>(v));
      }
      list->shapes.push_back(std::move(dims));
      char* buf = nullptr;
      Py_ssize_t len = 0;
      ok = PyBytes_AsStringAndSize(data, &buf, &len) == 0;
      if (ok) {
        const float* f = reinterpret_cast<const float*>(buf);
        list->data.emplace_back(f, f + len / sizeof(float));
      }
    }
    Py_XDECREF(key);
    Py_XDECREF(shape);
    Py_XDECREF(data);
  }
  Py_DECREF(obj);
  if (!ok) {
    take_py_error("MXNDListCreate: decode");
    return -1;
  }
  *out_length = static_cast<mx_uint>(n);
  *out = list.release();
  return 0;
}

int MXNDListGet(NDListHandle handle, mx_uint index, const char** out_key,
                const float** out_data, const mx_uint** out_shape,
                mx_uint* out_ndim) {
  auto* list = static_cast<NDList*>(handle);
  if (list == nullptr || out_key == nullptr || out_data == nullptr ||
      out_shape == nullptr || out_ndim == nullptr) {
    g_last_error = "MXNDListGet: null argument";
    return -1;
  }
  if (index >= list->keys.size()) {
    g_last_error = "MXNDListGet: index " + std::to_string(index) +
                   " >= length " + std::to_string(list->keys.size());
    return -1;
  }
  *out_key = list->keys[index].c_str();
  *out_data = list->data[index].data();
  *out_shape = list->shapes[index].data();
  *out_ndim = static_cast<mx_uint>(list->shapes[index].size());
  return 0;
}

int MXNDListFree(NDListHandle handle) {
  delete static_cast<NDList*>(handle);
  return 0;
}

}  // extern "C"
