// C predict API (reference: include/mxnet/c_predict_api.h +
// src/c_api/c_predict_api.cc — the flat ABI C/C++ applications link
// against to run a trained checkpoint without any Python on THEIR side).
//
// TPU-native re-design: the reference backs this ABI with its C++ graph
// executor; here the executor IS a jit-compiled XLA program, so the
// native layer embeds CPython and drives the same
// incubator_mxnet_tpu executor a Python caller would get — the C caller
// still sees only this ABI (handles + float buffers + MXGetLastError),
// and the heavy lifting stays in the compiled XLA program.
//
// ABI subset implemented (signatures match the reference):
//   MXGetLastError, MXPredCreate, MXPredSetInput, MXPredForward,
//   MXPredGetOutputShape, MXPredGetOutput, MXPredFree
//
// Build (the test does this; python3-config supplies the embed flags):
//   g++ -O2 -shared -fPIC -std=c++17 c_predict_api.cc \
//       $(python3-config --includes) $(python3-config --embed --ldflags) \
//       -o _c_predict_api.so
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

using mx_uint = uint32_t;
using PredictorHandle = void*;

namespace {

thread_local std::string g_last_error;

struct Predictor {
  PyObject* obj = nullptr;                  // predict_bridge.Predictor
  std::vector<mx_uint> shape_buf;           // owns MXPredGetOutputShape
};

// Initialize an interpreter if the host process doesn't have one (a pure
// C caller); release the GIL afterwards so every entry point can use the
// PyGILState API uniformly.  call_once: concurrent first MXPredCreate
// calls from a multithreaded C host must not race Py_InitializeEx.
std::once_flag g_py_init_once;
bool g_py_init_ok = false;

bool ensure_python() {
  std::call_once(g_py_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      if (!Py_IsInitialized()) return;
      PyEval_SaveThread();
    }
    g_py_init_ok = true;
  });
  if (!g_py_init_ok) {
    g_last_error = "embedded Python interpreter failed to initialize";
  }
  return g_py_init_ok;
}

// capture the current Python exception into g_last_error
void take_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_last_error = std::string(where) + ": ";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      g_last_error += c != nullptr ? c : "<unprintable>";
      Py_DECREF(s);
    }
  } else {
    g_last_error += "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  // PyObject_Str/PyUnicode_AsUTF8 may themselves have raised; the next
  // CPython call on this thread must start exception-clean
  PyErr_Clear();
}

PyObject* bridge() {
  // imported once per process; returns a borrowed-module new reference
  return PyImport_ImportModule(
      "incubator_mxnet_tpu.native.predict_bridge");
}

class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace

extern "C" {

const char* MXGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out) {
  if (out == nullptr || symbol_json_str == nullptr) {
    g_last_error = "MXPredCreate: null argument";
    return -1;
  }
  if (!ensure_python()) return -1;
  Gil gil;
  PyObject* mod = bridge();
  if (mod == nullptr) {
    take_py_error("MXPredCreate: import predict_bridge");
    return -1;
  }
  // inputs: [(key, (d0, d1, ...)), ...]
  PyObject* inputs = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    const mx_uint begin = input_shape_indptr[i];
    const mx_uint end = input_shape_indptr[i + 1];
    PyObject* shape = PyTuple_New(end - begin);
    for (mx_uint d = begin; d < end; ++d) {
      PyTuple_SET_ITEM(shape, d - begin,
                       PyLong_FromUnsignedLong(input_shape_data[d]));
    }
    PyObject* pair = PyTuple_New(2);
    PyTuple_SET_ITEM(pair, 0, PyUnicode_FromString(input_keys[i]));
    PyTuple_SET_ITEM(pair, 1, shape);
    PyList_SET_ITEM(inputs, i, pair);
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* res = PyObject_CallMethod(
      mod, "create", "sOiiO", symbol_json_str, params, dev_type, dev_id,
      inputs);
  Py_DECREF(params);
  Py_DECREF(inputs);
  Py_DECREF(mod);
  if (res == nullptr) {
    take_py_error("MXPredCreate");
    return -1;
  }
  auto* pred = new Predictor();
  pred->obj = res;
  *out = pred;
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || key == nullptr || data == nullptr) {
    g_last_error = "MXPredSetInput: null argument";
    return -1;
  }
  Gil gil;
  PyObject* bytes = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size) * 4);
  PyObject* res =
      PyObject_CallMethod(pred->obj, "set_input", "sO", key, bytes);
  Py_DECREF(bytes);
  if (res == nullptr) {
    take_py_error("MXPredSetInput");
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr) {
    g_last_error = "MXPredForward: null handle";
    return -1;
  }
  Gil gil;
  PyObject* res = PyObject_CallMethod(pred->obj, "forward", nullptr);
  if (res == nullptr) {
    take_py_error("MXPredForward");
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || shape_data == nullptr || shape_ndim == nullptr) {
    g_last_error = "MXPredGetOutputShape: null argument";
    return -1;
  }
  Gil gil;
  PyObject* res = PyObject_CallMethod(pred->obj, "get_output_shape", "I",
                                      index);
  if (res == nullptr) {
    take_py_error("MXPredGetOutputShape");
    return -1;
  }
  const Py_ssize_t n = PyTuple_Size(res);
  pred->shape_buf.resize(static_cast<size_t>(n));
  for (Py_ssize_t i = 0; i < n; ++i) {
    pred->shape_buf[static_cast<size_t>(i)] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GET_ITEM(res, i)));
  }
  Py_DECREF(res);
  *shape_data = pred->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr || data == nullptr) {
    g_last_error = "MXPredGetOutput: null argument";
    return -1;
  }
  Gil gil;
  PyObject* res =
      PyObject_CallMethod(pred->obj, "get_output", "I", index);
  if (res == nullptr) {
    take_py_error("MXPredGetOutput");
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &n) != 0) {
    Py_DECREF(res);
    take_py_error("MXPredGetOutput: bytes");
    return -1;
  }
  if (static_cast<Py_ssize_t>(size) * 4 != n) {
    g_last_error = "MXPredGetOutput: buffer size " +
                   std::to_string(size) + " floats != output " +
                   std::to_string(n / 4) + " floats";
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(data, buf, static_cast<size_t>(n));
  Py_DECREF(res);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  auto* pred = static_cast<Predictor*>(handle);
  if (pred == nullptr) return 0;
  {
    Gil gil;
    Py_XDECREF(pred->obj);
  }
  delete pred;
  return 0;
}

}  // extern "C"
