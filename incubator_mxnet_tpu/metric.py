"""Evaluation metrics (reference: python/mxnet/metric.py).

Same class surface and update semantics as the reference EvalMetric family;
computation happens in NumPy after an explicit device sync (metrics are the
reference's per-batch sync point too — its Module.fit calls update with
NDArrays and forces WaitToRead).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as _np

from .base import MXNetError

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "Perplexity", "Loss",
           "PearsonCorrelation", "CustomMetric", "create", "np"]

_REGISTRY: Dict[str, type] = {}


def _register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def _as_numpy(x) -> _np.ndarray:
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return _np.asarray(x)


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class EvalMetric:
    """Base class (reference: metric.EvalMetric)."""

    def __init__(self, name: str, output_names: Optional[Sequence[str]] = None,
                 label_names: Optional[Sequence[str]] = None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label: dict, pred: dict):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def get_config(self):
        return {"metric": self.__class__.__name__, "name": self.name,
                "output_names": self.output_names,
                "label_names": self.label_names}

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@_register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _to_list(labels), _to_list(preds)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_np.int64)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int64).reshape(-1)
            label = label.reshape(-1)
            if pred.shape != label.shape:
                raise MXNetError(
                    f"Accuracy: shape mismatch {pred.shape} vs "
                    f"{label.shape}")
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@_register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k
        if top_k <= 1:
            raise MXNetError("use Accuracy for top_k=1")

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_np.int64).reshape(-1)
            topk = _np.argsort(pred, axis=1)[:, -self.top_k:]
            self.sum_metric += float(
                (topk == label[:, None]).any(axis=1).sum())
            self.num_inst += len(label)


@_register
class F1(EvalMetric):
    """Binary F1 (reference: metric.F1; average='macro' over resets)."""

    def __init__(self, name="f1", average="macro", **kwargs):
        self.average = average
        super().__init__(name, **kwargs)

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            pred = _as_numpy(pred)
            label = _as_numpy(label).reshape(-1).astype(_np.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.reshape(-1) > 0.5).astype(_np.int64)
            pred = pred.reshape(-1)
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1
        prec = self._tp / max(self._tp + self._fp, 1e-12)
        rec = self._tp / max(self._tp + self._fn, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        self.sum_metric = f1 * self.num_inst


@_register
class MCC(EvalMetric):
    """Binary Matthews correlation coefficient (reference: metric.MCC).
    Accumulates the confusion matrix across updates."""

    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        self._tp = self._fp = self._tn = self._fn = 0.0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            pred = _as_numpy(pred)
            label = _as_numpy(label).reshape(-1).astype(_np.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                if pred.shape[-1] != 2:
                    raise MXNetError(
                        "MCC is a binary metric; got "
                        f"{pred.shape[-1]} prediction classes")
                pred = pred.argmax(axis=-1)
            else:
                pred = (pred.reshape(-1) > 0.5).astype(_np.int64)
            if ((label != 0) & (label != 1)).any():
                raise MXNetError("MCC is a binary metric; labels must "
                                 "be 0/1")
            pred = pred.reshape(-1)
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1
        denom = float(_np.sqrt((self._tp + self._fp)
                               * (self._tp + self._fn)
                               * (self._tn + self._fp)
                               * (self._tn + self._fn)))
        mcc = ((self._tp * self._tn - self._fp * self._fn)
               / max(denom, 1e-12))
        self.sum_metric = mcc * self.num_inst


@_register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@_register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@_register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            label = label.reshape(pred.shape)
            self.sum_metric += float(
                _np.sqrt(((label - pred) ** 2).mean()))
            self.num_inst += 1


@_register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label).ravel().astype(_np.int64)
            pred = _as_numpy(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float(-_np.log(prob + self.eps).sum())
            self.num_inst += label.shape[0]


@_register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@_register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label).ravel().astype(_np.int64)
            pred = _as_numpy(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                prob = _np.where(ignore, 1.0, prob)
                num -= int(ignore.sum())
            loss += float(-_np.log(_np.maximum(prob, 1e-10)).sum())
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.exp(self.sum_metric / self.num_inst)))


@_register
class Loss(EvalMetric):
    """Mean of a loss output (reference: metric.Loss)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _labels, preds):
        for pred in _to_list(preds):
            pred = _as_numpy(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


@_register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            if len(label) < 2:
                continue
            r = _np.corrcoef(label, pred)[0, 1]
            self.sum_metric += float(r)
            self.num_inst += 1


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def get_metric(self, index):
        return self.metrics[index]

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return names, values


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        for label, pred in zip(_to_list(labels), _to_list(preds)):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            out = self._feval(label, pred)
            if isinstance(out, tuple):
                s, n = out
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += out
                self.num_inst += 1


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference: metric.np)."""
    return CustomMetric(numpy_feval, name=name,
                        allow_extra_outputs=allow_extra_outputs)


def create(metric, *args, **kwargs) -> EvalMetric:
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = str(metric).lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy",
               "nll_loss": "negativeloglikelihood",
               "top_k_accuracy": "topkaccuracy", "top_k_acc": "topkaccuracy"}
    name = aliases.get(name, name)
    if name not in _REGISTRY:
        raise MXNetError(f"unknown metric {metric!r} "
                         f"(have {sorted(_REGISTRY)})")
    return _REGISTRY[name](*args, **kwargs)
