"""Base utilities: errors, env-var config plane, registries.

TPU-native re-design of the reference's dmlc-core base layer
(reference: 3rdparty/dmlc-core/include/dmlc/logging.h ``CHECK``/``dmlc::Error``;
src/c_api/c_api.cc TLS last-error).  There is no C ABI here: the Python layer
*is* the frontend, and JAX/XLA is the executor, so errors are plain Python
exceptions and the "env var config plane" (reference:
docs/static_site/src/pages/api/faq/env_var.md) maps MXNET_* names onto this
framework's knobs.
"""
from __future__ import annotations

import logging
import os
import threading

__all__ = [
    "MXNetError",
    "MXTPUError",
    "check_call",
    "getenv",
    "getenv_int",
    "getenv_float",
    "getenv_bool",
    "string_types",
    "numeric_types",
    "integer_types",
    "registry",
]

string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)


class MXNetError(RuntimeError):
    """Error raised by the framework.

    Name kept for API parity with the reference's ``mxnet.base.MXNetError``
    (reference: python/mxnet/base.py).  Async errors: because jax dispatches
    eagerly-but-asynchronously, device-side failures surface at the next
    blocking call (``wait_to_read``/``asnumpy``) exactly like the reference
    engine's deferred exception_ptr rethrow
    (reference: src/engine/threaded_engine.cc ThrowException).
    """


# Alias under the new framework's own name.
MXTPUError = MXNetError


def check_call(ret):
    """Parity shim for the ctypes-era ``check_call``; a no-op here since there
    is no C ABI return code to check (reference: python/mxnet/base.py)."""
    return ret


# ---------------------------------------------------------------------------
# Environment-variable config plane.
#
# The reference reads MXNET_* env vars via dmlc::GetEnv at use sites.  We keep
# the same names working (MXNET_*) and add MXTPU_* equivalents that win when
# both are set.  See docs/env_var.md for the supported list.
# ---------------------------------------------------------------------------

def getenv(name: str, default=None):
    """Read a config env var.  ``name`` is the canonical MXNET_* name; the
    MXTPU_* spelling takes precedence when present."""
    alt = name.replace("MXNET_", "MXTPU_", 1) if name.startswith("MXNET_") else None
    if alt is not None and alt in os.environ:
        return os.environ[alt]
    return os.environ.get(name, default)


def getenv_int(name: str, default: int = 0) -> int:
    v = getenv(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        raise MXNetError(f"env var {name} must be an int, got {v!r}")


def getenv_float(name: str, default: float = 0.0) -> float:
    v = getenv(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        raise MXNetError(f"env var {name} must be a number, got {v!r}")


def getenv_bool(name: str, default: bool = False) -> bool:
    v = getenv(name)
    if v is None or v == "":
        return default
    return str(v).lower() not in ("0", "false", "off", "no", "")


# Reference env vars accepted for compatibility but with no separate
# effect on TPU (docs/env_var.md explains each): XLA fuses/bulks
# unconditionally, PJRT owns the memory pool, collectives and
# accumulation/determinism policy are XLA's.  Setting one logs a
# one-time notice instead of silently ignoring it.
COMPAT_ACCEPTED_ENV = (
    "MXNET_EXEC_BULK_EXEC_TRAIN",
    "MXNET_EXEC_BULK_EXEC_INFERENCE",
    "MXNET_EXEC_ENABLE_ADDTO",
    "MXNET_PROFILER_MODE",
    "MXNET_GPU_MEM_POOL_TYPE",
    "MXNET_GPU_MEM_POOL_RESERVE",
    "MXNET_KVSTORE_BIGARRAY_BOUND",
    "MXNET_KVSTORE_USETREE",
    "MXNET_SAFE_ACCUMULATION",
    "MXNET_ENFORCE_DETERMINISM",
)

_compat_env_logged = False


def log_compat_env_once() -> list:
    """One-time notice for set-but-ignored reference env vars; returns
    the names that were set (import-time hook, also handy in tests)."""
    global _compat_env_logged
    seen = [n for n in COMPAT_ACCEPTED_ENV if getenv(n) not in (None, "")]
    if seen and not _compat_env_logged:
        logging.getLogger("incubator_mxnet_tpu").info(
            "accepted for compatibility (no separate effect on TPU): %s",
            ", ".join(seen))
    _compat_env_logged = True
    return seen


# ---------------------------------------------------------------------------
# Lightweight name->object registry, the stand-in for the reference's
# dmlc registry + NNVM op registry (reference: 3rdparty/tvm/nnvm op registry,
# python/mxnet/registry.py).
# ---------------------------------------------------------------------------

class _Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._store: dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, name: str, obj=None, *, allow_override: bool = False):
        def _do(o):
            key = name.lower()
            with self._lock:
                if key in self._store and not allow_override:
                    raise MXNetError(
                        f"{self.kind} '{name}' is already registered")
                self._store[key] = o
            return o
        if obj is None:
            return _do
        return _do(obj)

    def get(self, name: str):
        try:
            return self._store[name.lower()]
        except KeyError:
            raise MXNetError(
                f"unknown {self.kind} '{name}'; registered: "
                f"{sorted(self._store)}") from None

    def __contains__(self, name: str) -> bool:
        return name.lower() in self._store

    def keys(self):
        return sorted(self._store)


_registries: dict[str, _Registry] = {}


def registry(kind: str) -> _Registry:
    """Get (or create) the global registry for ``kind`` ('optimizer',
    'initializer', 'metric', 'kvstore', ...)."""
    if kind not in _registries:
        _registries[kind] = _Registry(kind)
    return _registries[kind]
