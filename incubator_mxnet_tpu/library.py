"""Custom-op shared-library loader (reference:
python/mxnet/library.py ``load`` + ``MXLoadLib`` in src/c_api/c_api.cc,
ABI in include/mxnet/lib_api.h).

TPU-native re-design: the library implements the flat C surface declared
in ``native/lib_api.h`` (host float32 kernels + shape inference).  Each
loaded op is wrapped in ``jax.pure_callback`` with the library-inferred
output shape, then registered under ``mx.nd.<name>`` — so it runs inside
jitted programs as a host callback while everything around it stays
compiled.  Loaded ops are not differentiable (the reference's loadable
backward is a follow-up; autograd raises if a grad is requested through
one).
"""
from __future__ import annotations

import ctypes
import os

import numpy as _np

from .base import MXNetError

__all__ = ["load", "loaded_ops"]

_MAX_NDIM = 8
_loaded = {}     # path -> set of op names
_handles = []    # keep CDLLs alive for the process lifetime


def loaded_ops():
    """Mapping of library path -> list of op names loaded from it."""
    return {path: sorted(ops) for path, ops in _loaded.items()}


def _check(lib, sym):
    if not hasattr(lib, sym):
        raise MXNetError(
            f"library does not export required symbol '{sym}' "
            "(see incubator_mxnet_tpu/native/lib_api.h)")


def _make_op(lib, name):
    """Build the Python-callable op for a library op ``name``."""
    from .context import current_context
    from .ndarray.ndarray import NDArray, _invoke

    def infer_shape(shapes):
        n = len(shapes)
        ndims = (ctypes.c_int * n)(*[len(s) for s in shapes])
        arrs = [(ctypes.c_int64 * len(s))(*s) for s in shapes]
        ptrs = (ctypes.POINTER(ctypes.c_int64) * n)(
            *[ctypes.cast(a, ctypes.POINTER(ctypes.c_int64))
              for a in arrs])
        out = (ctypes.c_int64 * _MAX_NDIM)()
        nd = lib.mxtpu_lib_op_infer_shape(name.encode(), n, ptrs, ndims,
                                          out)
        if nd < 0:
            raise MXNetError(
                f"custom op '{name}': infer_shape failed (code {nd}) for "
                f"input shapes {shapes}")
        if nd > _MAX_NDIM:
            raise MXNetError(
                f"custom op '{name}': infer_shape returned ndim {nd} > "
                f"MXTPU_LIB_MAX_NDIM ({_MAX_NDIM}) — broken library")
        return tuple(int(out[i]) for i in range(nd))

    def host_compute(out_shape, *arrays):
        arrays = [_np.ascontiguousarray(a, _np.float32) for a in arrays]
        n = len(arrays)
        ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        sarrs = [(ctypes.c_int64 * a.ndim)(*a.shape) for a in arrays]
        sptrs = (ctypes.POINTER(ctypes.c_int64) * n)(
            *[ctypes.cast(s, ctypes.POINTER(ctypes.c_int64))
              for s in sarrs])
        iptrs = (ctypes.POINTER(ctypes.c_float) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        out = _np.empty(out_shape, _np.float32)
        oshape = (ctypes.c_int64 * len(out_shape))(*out_shape)
        rc = lib.mxtpu_lib_op_compute(
            name.encode(), n, iptrs, sptrs, ndims,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), oshape,
            len(out_shape))
        if rc != 0:
            raise MXNetError(f"custom op '{name}': compute failed "
                             f"(code {rc})")
        return out

    def op(*inputs, **kwargs):
        if kwargs:
            raise MXNetError(
                f"custom op '{name}' takes only tensor inputs")
        nds = [x if isinstance(x, NDArray)
               else NDArray(_np.asarray(x, _np.float32))
               for x in inputs]
        out_shape = infer_shape([x.shape for x in nds])

        def fn(*jarrs):
            import functools
            import jax
            import jax.numpy as jnp
            return jax.pure_callback(
                functools.partial(host_compute, out_shape),
                jax.ShapeDtypeStruct(out_shape, jnp.float32),
                *[a.astype(jnp.float32) for a in jarrs],
                vmap_method="sequential")
        return _invoke(fn, nds, name=name, differentiable=False)

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = (f"Custom op '{name}' loaded from a shared library "
                  "(host float32 kernel via jax.pure_callback; "
                  "not differentiable).")
    return op


def load(path, verbose=True):
    """Load a custom-op library and register its ops under ``mx.nd``
    (reference: mx.library.load -> MXLoadLib).  Returns the list of op
    names registered."""
    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise MXNetError(f"library not found: {path}")
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        raise MXNetError(f"cannot load library {path}: {e}") from e
    for sym in ("mxtpu_lib_api_version", "mxtpu_lib_num_ops",
                "mxtpu_lib_op_name", "mxtpu_lib_op_infer_shape",
                "mxtpu_lib_op_compute"):
        _check(lib, sym)
    lib.mxtpu_lib_op_name.restype = ctypes.c_char_p
    version = lib.mxtpu_lib_api_version()
    if version != 1:
        raise MXNetError(
            f"library {path} has ABI version {version}; this build "
            "supports version 1")

    from . import ndarray as nd_mod
    # validate and build every op first, register atomically after — a
    # bad op must not leave earlier ops half-registered.  Only names this
    # same path registered before are overwritable (idempotent reload);
    # clashes with built-ins OR with other libraries' ops are refused.
    already = _loaded.get(path, set())
    ops = {}
    for i in range(lib.mxtpu_lib_num_ops()):
        raw = lib.mxtpu_lib_op_name(i)
        if not raw:
            raise MXNetError(f"library {path}: op {i} has no name")
        name = raw.decode()
        if not name.isidentifier():
            raise MXNetError(
                f"library {path}: op name '{name}' is not a valid "
                "identifier")
        if hasattr(nd_mod, name) and name not in already:
            raise MXNetError(
                f"library {path}: op name '{name}' collides with an "
                "existing mx.nd function; rename the op")
        ops[name] = _make_op(lib, name)
    for stale in already - set(ops):
        # reloaded library no longer exports this op
        if hasattr(nd_mod, stale):
            delattr(nd_mod, stale)
    for name, fn in ops.items():
        setattr(nd_mod, name, fn)
        if verbose:
            import logging
            logging.getLogger(__name__).info(
                "loaded custom op '%s' from %s", name, path)
    _loaded[path] = set(ops)
    _handles.append(lib)
    return sorted(ops)
