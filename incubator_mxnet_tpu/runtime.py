"""``mx.runtime`` — runtime feature detection (reference:
python/mxnet/runtime.py; src/libinfo.cc ``MXLibInfoFeatures``).

The reference's feature matrix reports compile-time flags (CUDA? MKLDNN?
...).  This build's equivalents are runtime facts about the jax install
and attached devices.
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _detect():
    import jax
    try:
        platforms = {d.platform for d in jax.devices()}
    except Exception:
        platforms = set()
    has_pallas = True
    try:
        import jax.experimental.pallas  # noqa: F401
    except Exception:
        has_pallas = False
    feats = {
        # accelerator surface
        "TPU": "tpu" in platforms or "axon" in platforms,
        "CUDA": False,          # by design: no CUDA in this build
        "CUDNN": False,
        "MKLDNN": False,
        "XLA": True,
        "PALLAS": has_pallas,
        "BF16": True,
        "F16C": True,
        # framework capabilities (reference flag names)
        "DIST_KVSTORE": True,   # XLA collectives over ICI/DCN
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": True,
        "PROFILER": True,
        "OPENCV": False,
        "TENSORRT": False,
        "TVM_OP": False,
        "SSE": True,
        "DEBUG": False,
    }
    return feats


class Features(dict):
    """reference: mx.runtime.Features — dict of Feature with
    ``is_enabled``."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name):
        name = name.upper()
        return name in self and self[name].enabled

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"


def feature_list():
    return list(Features().values())
