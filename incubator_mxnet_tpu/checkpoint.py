"""Asynchronous, preemption-safe checkpointing (SURVEY §5.3: the
reference's recovery story is restart-from-epoch-checkpoint via
Module.fit callbacks — python/mxnet/callback.py do_checkpoint,
model.py save_checkpoint; this module EXCEEDS that with the
goodput-relevant pieces a pod run needs):

* **async**: the device→host copy happens on the caller's thread (cheap,
  and required — arrays must be snapshotted before the next step mutates
  them), the file write happens on a background thread so the train loop
  never blocks on storage;
* **atomic**: writes go to a temp file + os.replace, so a preemption
  mid-write never corrupts the newest checkpoint;
* **full training state**: ``save(step, params, trainer=..., scaler=...,
  epoch=...)`` additionally snapshots the Trainer's updater/optimizer
  states, the LossScaler, and the RNG key streams, committed by a
  manifest written LAST — a checkpoint without its manifest is
  incomplete by definition, so a kill between the params publish and the
  manifest publish can never shadow the previous complete step;
* **retried storage**: every publish runs under ``fault.retry_call``
  (site ``checkpoint.write``) — a transient IOError costs a retry, not
  the checkpoint;
* **retention**: keep the last k checkpoints (default 3), seeded from
  ALL steps already on disk so a restarted run keeps garbage-collecting
  its predecessor's files; orphaned ``*.tmp-<pid>`` files from an
  interrupted write are swept at startup;
* **resume**: ``latest_checkpoint`` finds the newest complete params
  file; ``latest_resumable_step`` the newest step with a full-state
  manifest; ``restore_into`` rehydrates params + trainer + scaler + RNG
  in one call.

Format: the same reference-compatible ``.params`` container
(ndarray/utils.save) everything else uses, named ``<prefix>-NNNNNNN.params``
— readable by load_checkpoint/load_parameters tooling.  Full-state
checkpoints add ``<prefix>-NNNNNNN.states`` (the Trainer's pickled
updater/optimizer states) and ``<prefix>-NNNNNNN.meta.json`` (the
manifest: step/epoch, RNG key streams, inlined scaler state, file map).
"""
from __future__ import annotations

import json
import os
import re
import threading
from typing import Dict, List, Optional

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["AsyncCheckpointer", "latest_checkpoint", "all_checkpoints",
           "latest_resumable_step"]

MANIFEST_FORMAT = 1


def _step_path(prefix: str, step: int) -> str:
    return f"{prefix}-{step:07d}.params"


def _states_path(prefix: str, step: int) -> str:
    return f"{prefix}-{step:07d}.states"


def _meta_path(prefix: str, step: int) -> str:
    return f"{prefix}-{step:07d}.meta.json"


def _scan(prefix: str, suffix: str) -> List[int]:
    """Steps for which ``<prefix>-NNNNNNN<suffix>`` exists, sorted."""
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    # exact-prefix anchor: 'm' must not match 'model-*'; 7+ digits so
    # steps >= 10^7 (which format wider than the zero-padding) still parse
    pat = re.compile(rf"^{re.escape(base)}-(\d{{7,}}){re.escape(suffix)}$")
    if not os.path.isdir(d):
        return []
    steps = []
    for name in os.listdir(d):
        m = pat.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def all_checkpoints(prefix: str) -> List[int]:
    """All complete checkpoint steps for ``prefix``, sorted ascending."""
    return _scan(prefix, ".params")


def latest_checkpoint(prefix: str) -> Optional[int]:
    """Newest complete checkpoint step for ``prefix``, or None."""
    steps = all_checkpoints(prefix)
    return steps[-1] if steps else None


def latest_resumable_step(prefix: str) -> Optional[int]:
    """Newest step with a COMMITTED full-state checkpoint: the manifest
    (written last) and the params file it points at must both exist, so
    a write interrupted anywhere short of the manifest publish is
    invisible here."""
    have_params = set(all_checkpoints(prefix))
    for step in reversed(_scan(prefix, ".meta.json")):
        if step in have_params:
            return step
    return None


class AsyncCheckpointer:
    """Background checkpoint writer.

    Usage::

        ckpt = AsyncCheckpointer("ckpt/model", keep=3)
        for step, batch in enumerate(loader):
            ...train...
            if step % 500 == 0:
                ckpt.save(step, {name: p.data() for name, p in params},
                          trainer=trainer)
        ckpt.wait_until_finished()    # before exit
    """

    def __init__(self, prefix: str, keep: int = 3):
        d = os.path.dirname(prefix)
        if d:
            os.makedirs(d, exist_ok=True)
        self._prefix = prefix
        self._keep = max(1, int(keep))
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._sweep_orphans()
        # seed retention from EVERY step already on disk — a restarted
        # run must keep GC-ing its predecessor's checkpoints past `keep`
        self._saved_steps: List[int] = all_checkpoints(prefix)

    def _sweep_orphans(self):
        """Remove ``*.tmp-<pid>`` leftovers of a write that a preemption
        interrupted before its atomic os.replace — otherwise a
        repeatedly-preempted run leaks temp files without bound."""
        d = os.path.dirname(self._prefix) or "."
        base = os.path.basename(self._prefix)
        pat = re.compile(
            rf"^{re.escape(base)}-\d{{7,}}"
            rf"\.(?:params|states|meta\.json)\.tmp-\d+$")
        if not os.path.isdir(d):
            return
        for name in os.listdir(d):
            if pat.match(name):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass

    # ------------------------------------------------------------------
    def save(self, step: int, params: Dict[str, NDArray], trainer=None,
             scaler=None, epoch: Optional[int] = None,
             extra: Optional[dict] = None):
        """Snapshot ``params`` (and optionally the full training state)
        and write asynchronously.  Raises any error from the PREVIOUS
        save (errors never vanish silently).

        With only ``(step, params)`` this writes the legacy single
        ``.params`` file.  Passing ``trainer`` / ``scaler`` / ``epoch`` /
        ``extra`` upgrades it to a full-state checkpoint: the Trainer's
        updater+optimizer states (``Trainer.get_states()``), the
        LossScaler state, the RNG key streams, and ``extra`` are
        captured ON THIS THREAD (so the training loop may mutate
        everything freely after return) and committed by a
        ``.meta.json`` manifest published after all data files."""
        self.wait_until_finished()
        # snapshot on the caller's thread: after return the trainer may
        # mutate the arrays freely
        snap = {}
        for k, v in params.items():
            if isinstance(v, NDArray):
                snap[k] = v.asnumpy().copy()
            else:
                snap[k] = _np.asarray(v).copy()
        states = trainer.get_states() if trainer is not None else None
        manifest = None
        if trainer is not None or scaler is not None or epoch is not None \
                or extra is not None:
            from . import random as _random
            manifest = {
                "format": MANIFEST_FORMAT,
                "step": int(step),
                "rng": _random.get_state(),
                "files": {
                    "params": os.path.basename(
                        _step_path(self._prefix, step)),
                },
            }
            if epoch is not None:
                manifest["epoch"] = int(epoch)
            if states is not None:
                manifest["files"]["states"] = os.path.basename(
                    _states_path(self._prefix, step))
            if scaler is not None:
                manifest["scaler"] = scaler.get_state()
            if extra is not None:
                manifest["extra"] = extra
        self._thread = threading.Thread(
            target=self._write, args=(step, snap, states, manifest),
            daemon=True)
        self._thread.start()

    def save_sync(self, step: int, params: Dict[str, NDArray],
                  **kwargs) -> None:
        """:meth:`save` + :meth:`wait_until_finished` in one call — the
        emergency/preemption path.  A SIGTERM'd training loop (see
        ``lifecycle.shutdown_requested`` and docs/robustness.md) calls
        this at a STEP BOUNDARY so the snapshot is a consistent,
        bit-identically resumable state, and blocks until the manifest
        is committed before exiting."""
        self.save(step, params, **kwargs)
        self.wait_until_finished()

    def _publish(self, path: str, write_fn):
        """tmp-write + atomic rename, with transient storage errors
        absorbed by retry (the injection site fires before any bytes are
        written, so a retried attempt replays cleanly)."""
        from . import fault as _fault
        tmp = f"{path}.tmp-{os.getpid()}"

        def attempt():
            _fault.inject("checkpoint.write")
            write_fn(tmp)
            os.replace(tmp, path)    # atomic publish

        _fault.retry_call(attempt, site="checkpoint.write")

    def _write(self, step: int, snap: Dict[str, _np.ndarray],
               states: Optional[bytes], manifest: Optional[dict]):
        try:
            from .ndarray import utils as nd_utils
            # host numpy straight into the container format — no
            # host->device->host round trip on the background thread
            self._publish(_step_path(self._prefix, step),
                          lambda tmp: nd_utils.save(tmp, snap))
            if states is not None:
                def write_states(tmp, _b=states):
                    with open(tmp, "wb") as f:
                        f.write(_b)
                self._publish(_states_path(self._prefix, step),
                              write_states)
            if manifest is not None:
                # the COMMIT record: published last, so every file it
                # names is already in place when it becomes visible
                def write_meta(tmp, _m=manifest):
                    with open(tmp, "w") as f:
                        json.dump(_m, f, indent=1)
                self._publish(_meta_path(self._prefix, step), write_meta)
            self._saved_steps.append(step)
            self._gc()
        except BaseException as e:   # surfaced on the next save()/wait
            self._error = e

    def _gc(self):
        self._saved_steps.sort()
        while len(self._saved_steps) > self._keep:
            step = self._saved_steps.pop(0)
            for path in (_meta_path(self._prefix, step),
                         _states_path(self._prefix, step),
                         _step_path(self._prefix, step)):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def wait_until_finished(self):
        """Block until the in-flight write completes; re-raise its error."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise MXNetError(f"async checkpoint write failed: {err}") \
                from err

    # ------------------------------------------------------------------
    def restore(self, step: Optional[int] = None) -> Dict[str, NDArray]:
        """Load the params at ``step`` (default: newest)."""
        from .ndarray import utils as nd_utils
        if step is None:
            step = latest_checkpoint(self._prefix)
            if step is None:
                raise MXNetError(
                    f"no checkpoint found for prefix {self._prefix!r}")
        return nd_utils.load(_step_path(self._prefix, step))

    def latest_resumable_step(self) -> Optional[int]:
        return latest_resumable_step(self._prefix)

    def restore_full(self, step: Optional[int] = None) -> dict:
        """Load a full-state checkpoint: the parsed manifest plus
        ``params`` (name → NDArray) and raw ``trainer_states`` bytes
        (None when the checkpoint carried no trainer)."""
        from .ndarray import utils as nd_utils
        if step is None:
            step = self.latest_resumable_step()
            if step is None:
                raise MXNetError(
                    f"no resumable (full-state) checkpoint for prefix "
                    f"{self._prefix!r}")
        meta = _meta_path(self._prefix, step)
        try:
            with open(meta) as f:
                state = json.load(f)
        except OSError as e:
            raise MXNetError(
                f"checkpoint step {step} has no manifest {meta!r} — not "
                f"a full-state checkpoint (use restore())") from e
        state["params"] = nd_utils.load(_step_path(self._prefix, step))
        state["trainer_states"] = None
        if state.get("files", {}).get("states"):
            with open(_states_path(self._prefix, step), "rb") as f:
                state["trainer_states"] = f.read()
        return state

    def restore_into(self, params=None, trainer=None, scaler=None,
                     step: Optional[int] = None) -> Optional[int]:
        """Rehydrate a killed run from the newest complete full-state
        checkpoint (or ``step``): copy saved arrays into ``params`` (a
        ParameterDict / name→Parameter mapping), restore the Trainer's
        updater/optimizer states, the LossScaler, and the RNG key
        streams.  Returns the restored step, or None when no full-state
        checkpoint exists — callers start fresh in that case."""
        if step is None:
            step = self.latest_resumable_step()
            if step is None:
                return None
        state = self.restore_full(step)
        if params is not None:
            for name, arr in state["params"].items():
                if name not in params:
                    continue
                p = params[name]
                if (getattr(p, "_data", 1) is None
                        and getattr(p, "_deferred_init", None) is not None):
                    # net not yet shaped by a forward pass: the saved
                    # array knows the shape — finish deferred init here
                    p.shape = arr.shape
                    p._finish_deferred_init()
                p.set_data(arr)
        if trainer is not None and state.get("trainer_states"):
            trainer.set_states(state["trainer_states"])
        if scaler is not None and state.get("scaler"):
            scaler.set_state(state["scaler"])
        if state.get("rng"):
            from . import random as _random
            _random.set_state(state["rng"])
        return step
