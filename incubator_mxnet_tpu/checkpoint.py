"""Asynchronous, preemption-safe checkpointing (SURVEY §5.3: the
reference's recovery story is restart-from-epoch-checkpoint via
Module.fit callbacks — python/mxnet/callback.py do_checkpoint,
model.py save_checkpoint; this module EXCEEDS that with the
goodput-relevant pieces a pod run needs):

* **async**: the device→host copy happens on the caller's thread (cheap,
  and required — arrays must be snapshotted before the next step mutates
  them), the file write happens on a background thread so the train loop
  never blocks on storage;
* **atomic**: writes go to a temp file + os.replace, so a preemption
  mid-write never corrupts the newest checkpoint;
* **retention**: keep the last k checkpoints (default 3);
* **resume**: ``latest_checkpoint`` finds the newest complete step.

Format: the same reference-compatible ``.params`` container
(ndarray/utils.save) everything else uses, named ``<prefix>-NNNNNNN.params``
— readable by load_checkpoint/load_parameters tooling.
"""
from __future__ import annotations

import os
import re
import threading
from typing import Dict, List, Optional

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["AsyncCheckpointer", "latest_checkpoint"]


def _step_path(prefix: str, step: int) -> str:
    return f"{prefix}-{step:07d}.params"


def latest_checkpoint(prefix: str) -> Optional[int]:
    """Newest complete checkpoint step for ``prefix``, or None."""
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    # exact-prefix anchor: 'm' must not match 'model-*'; 7+ digits so
    # steps >= 10^7 (which format wider than the zero-padding) still parse
    pat = re.compile(rf"^{re.escape(base)}-(\d{{7,}})\.params$")
    best = None
    if not os.path.isdir(d):
        return None
    for name in os.listdir(d):
        m = pat.match(name)
        if m:
            step = int(m.group(1))
            best = step if best is None else max(best, step)
    return best


class AsyncCheckpointer:
    """Background checkpoint writer.

    Usage::

        ckpt = AsyncCheckpointer("ckpt/model", keep=3)
        for step, batch in enumerate(loader):
            ...train...
            if step % 500 == 0:
                ckpt.save(step, {name: p.data() for name, p in params})
        ckpt.wait_until_finished()    # before exit
    """

    def __init__(self, prefix: str, keep: int = 3):
        d = os.path.dirname(prefix)
        if d:
            os.makedirs(d, exist_ok=True)
        self._prefix = prefix
        self._keep = max(1, int(keep))
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._saved_steps: List[int] = []
        lt = latest_checkpoint(prefix)
        if lt is not None:
            self._saved_steps.append(lt)

    # ------------------------------------------------------------------
    def save(self, step: int, params: Dict[str, NDArray]):
        """Snapshot ``params`` and write asynchronously.  Raises any error
        from the PREVIOUS save (errors never vanish silently)."""
        self.wait_until_finished()
        # snapshot on the caller's thread: after return the trainer may
        # mutate the arrays freely
        snap = {}
        for k, v in params.items():
            if isinstance(v, NDArray):
                snap[k] = v.asnumpy().copy()
            else:
                snap[k] = _np.asarray(v).copy()
        self._thread = threading.Thread(
            target=self._write, args=(step, snap), daemon=True)
        self._thread.start()

    def _write(self, step: int, snap: Dict[str, _np.ndarray]):
        try:
            from .ndarray import utils as nd_utils
            final = _step_path(self._prefix, step)
            tmp = f"{final}.tmp-{os.getpid()}"
            # host numpy straight into the container format — no
            # host->device->host round trip on the background thread
            nd_utils.save(tmp, snap)
            os.replace(tmp, final)    # atomic publish
            self._saved_steps.append(step)
            self._gc()
        except BaseException as e:   # surfaced on the next save()/wait
            self._error = e

    def _gc(self):
        self._saved_steps.sort()
        while len(self._saved_steps) > self._keep:
            step = self._saved_steps.pop(0)
            try:
                os.unlink(_step_path(self._prefix, step))
            except OSError:
                pass

    def wait_until_finished(self):
        """Block until the in-flight write completes; re-raise its error."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise MXNetError(f"async checkpoint write failed: {err}") \
                from err

    # ------------------------------------------------------------------
    def restore(self, step: Optional[int] = None) -> Dict[str, NDArray]:
        """Load the checkpoint at ``step`` (default: newest)."""
        from .ndarray import utils as nd_utils
        if step is None:
            step = latest_checkpoint(self._prefix)
            if step is None:
                raise MXNetError(
                    f"no checkpoint found for prefix {self._prefix!r}")
        return nd_utils.load(_step_path(self._prefix, step))
