"""Weight initializers (reference: python/mxnet/initializer.py).

Each initializer is a callable object writing into an NDArray; string aliases
(``init='xavier'``) resolve through the registry exactly like the reference's
``mx.init.register`` mechanism.  RNG flows through ``mx.random`` so
``mx.random.seed`` reproduces initializations.
"""
from __future__ import annotations

import json
import math
import re

import numpy as _np

from .base import MXNetError

__all__ = ["Initializer", "Zero", "One", "Constant", "Uniform", "Normal",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "InitDesc", "register", "create"]

_REGISTRY = {}


def register(klass):
    """Register an initializer under its lowercased class name
    (reference: mx.init.register decorator)."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(init, **kwargs) -> "Initializer":
    if isinstance(init, Initializer):
        return init
    if init is None:
        return Uniform()
    if isinstance(init, str):
        name = init.lower()
        if name not in _REGISTRY:
            raise MXNetError(f"unknown initializer {init!r}; "
                             f"registered: {sorted(_REGISTRY)}")
        return _REGISTRY[name](**kwargs)
    raise TypeError(f"cannot create Initializer from {type(init)}")


class InitDesc(str):
    """Parameter-name string carrying init attrs (reference:
    python/mxnet/initializer.py InitDesc)."""
    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer.  Subclasses implement ``_init_weight``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, name, arr):
        """Dispatch by parameter name suffix (reference
        Initializer.__call__ legacy pattern)."""
        if not isinstance(name, str):
            name = str(name)
        if name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_one(name, arr)
        elif name.endswith("beta"):
            self._init_zero(name, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(name, arr)
        elif (name.endswith("running_var") or name.endswith("moving_var")
              or name.endswith("moving_avg")):
            self._init_one(name, arr)
        else:
            self._init_weight(name, arr)

    init_weight = __call__

    # -- helpers -----------------------------------------------------------
    def _set(self, arr, np_value):
        arr[:] = _np.asarray(np_value, dtype=arr.dtype)

    def _init_zero(self, name, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_one(self, name, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_bias(self, name, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _rng(self):
        from . import random as mxrand
        return mxrand.numpy_rng()

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._set(arr, _np.zeros(arr.shape))


_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._set(arr, _np.ones(arr.shape))


_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        self._set(arr, _np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        self._set(arr, self._rng().uniform(-self.scale, self.scale,
                                           arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        self._set(arr, self._rng().normal(0.0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        rng = self._rng()
        if self.rand_type == "uniform":
            tmp = rng.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = rng.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        self._set(arr, (self.scale * q).reshape(arr.shape))


@register
class Xavier(Initializer):
    """reference: python/mxnet/initializer.py Xavier — factor_type
    avg|in|out, rnd_type uniform|gaussian."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier requires >=2D weight, got {shape} for {name}")
        hw_scale = float(_np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"invalid factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / factor)
        rng = self._rng()
        if self.rnd_type == "uniform":
            self._set(arr, rng.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, rng.normal(0, scale, shape))
        else:
            raise MXNetError(f"invalid rnd_type {self.rnd_type}")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        Xavier.__init__(self, "gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for Deconvolution)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (reference LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype=_np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # [i, f, g, o] order
        self._set(arr, b)


@register
class Mixed(Initializer):
    """Route parameters to initializers by regex on the parameter name
    (reference: mx.init.Mixed).  First matching pattern wins; a '.*'
    catch-all is conventional as the last entry.  ``initializers``
    entries may be Initializer objects or dumps()-style ``[name,
    kwargs]`` specs (so Mixed itself round-trips through dumps())."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed: len(patterns) != len(initializers)")
        initializers = [
            _REGISTRY[i[0]](**i[1]) if isinstance(i, (list, tuple))
            else i for i in initializers]
        super().__init__(
            patterns=list(patterns),
            initializers=[json.loads(i.dumps()) for i in initializers])
        self._map = [(re.compile(p), init) for p, init in
                     zip(patterns, initializers)]

    def __call__(self, name, arr):
        if not isinstance(name, str):
            name = str(name)
        for pat, init in self._map:
            if pat.match(name):
                init(name, arr)
                return
        raise MXNetError(
            f"Mixed: no pattern matched parameter '{name}'; add a '.*' "
            "catch-all as the last entry")
