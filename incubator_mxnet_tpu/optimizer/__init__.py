"""``mx.optimizer`` (reference: python/mxnet/optimizer/)."""
from .optimizer import *  # noqa: F401,F403
from .optimizer import __all__ as _a

__all__ = list(_a)
