"""``mx.optimizer`` (reference: python/mxnet/optimizer/)."""
from .optimizer import *  # noqa: F401,F403
from .optimizer import __all__ as _a
from .fused import FusedUpdater  # noqa: F401

__all__ = list(_a) + ["FusedUpdater"]
