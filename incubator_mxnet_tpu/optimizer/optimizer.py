"""Optimizers (reference: python/mxnet/optimizer/optimizer.py).

Update rules delegate to the optimizer ops in
``ndarray/optimizer_ops.py`` (reference kernels: src/operator/optimizer_op.cc)
so the Python classes stay thin — hyperparameter bookkeeping (lr scheduling,
per-param lr/wd multipliers, update counts, multi-precision master weights)
matching the reference class for class.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as _np

from ..base import MXNetError
from ..ndarray import ndarray as _ndmod
from ..ndarray.ndarray import NDArray
from ..ndarray import optimizer_ops as _oo

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "Adamax", "Nadam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "FTML", "Signum", "SignSGD",
           "LAMB", "LARS", "AdamW", "GroupAdaGrad", "SGLD", "DCASGD", "Test", "create",
           "register", "get_updater", "Updater"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    name = name.lower()
    if name not in _REGISTRY:
        raise MXNetError(f"unknown optimizer {name!r}; "
                         f"registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


class Optimizer:
    """Base optimizer (reference: Optimizer).  State is created lazily per
    parameter index; ``update(index, weight, grad, state)`` applies one
    step."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}

    create_optimizer = staticmethod(create)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == _np.float16:
            w32 = weight.astype(_np.float32)
            return (w32, self.create_state(index, w32))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            w32, inner = state
            self.update(index, w32, grad.astype(_np.float32), inner)
            weight._set_data(w32._data.astype(weight._data.dtype))
        else:
            self.update(index, weight, grad, state)

    # -- hyperparams -------------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot set lr directly")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    @learning_rate.setter
    def learning_rate(self, lr):
        self.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index],
                              self.num_update)

    def _get_lr(self, index):
        lr = self.learning_rate
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def __repr__(self):
        return f"{self.__class__.__name__}(lr={self.learning_rate})"


def _zeros_like(weight, dtype=None):
    import jax.numpy as jnp
    return NDArray(jnp.zeros(weight.shape,
                             dtype or weight._data.dtype), ctx=weight.ctx)


@register
class SGD(Optimizer):
    """reference: SGD — mom = momentum*mom - lr*(grad + wd*w); w += mom."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0)
        if state is not None:
            _oo.sgd_mom_update(weight, grad, state, momentum=self.momentum,
                               lazy_update=self.lazy_update, **kw)
        else:
            _oo.sgd_update(weight, grad, lazy_update=self.lazy_update, **kw)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            w32, mom = state
            lr, wd = self._get_lr(index), self._get_wd(index)
            self._update_count(index)
            kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=self.clip_gradient or -1.0)
            if mom is not None:
                _oo.mp_sgd_mom_update(weight, grad, mom, w32,
                                      momentum=self.momentum, **kw)
            else:
                _oo.mp_sgd_update(weight, grad, w32, **kw)
        else:
            self.update(index, weight, grad, state)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference: NAG)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0)
        if state is not None:
            _oo.nag_mom_update(weight, grad, state, momentum=self.momentum,
                               **kw)
        else:
            _oo.sgd_update(weight, grad, **kw)


@register
class Adam(Optimizer):
    """reference: Adam — bias-corrected lr passed into adam_update."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        _oo.adam_update(weight, grad, mean, var, lr=lr, beta1=self.beta1,
                        beta2=self.beta2, epsilon=self.epsilon, wd=wd,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=self.clip_gradient or -1.0,
                        lazy_update=self.lazy_update)


@register
class Adamax(Optimizer):
    """reference: Adamax (infinity-norm Adam)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        m, u = state
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad + wd * weight._data
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        new_m = self.beta1 * m._data + (1 - self.beta1) * g
        new_u = jnp.maximum(self.beta2 * u._data, jnp.abs(g))
        m._set_data(new_m)
        u._set_data(new_u)
        weight._set_data(weight._data - lr * new_m / (new_u + 1e-8))


@register
class Nadam(Optimizer):
    """reference: Nadam (Adam + Nesterov momentum schedule)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad + wd * weight._data
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 **
                                   (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule *= momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        grad_prime = g / (1. - self.m_schedule)
        new_m = self.beta1 * m._data + (1. - self.beta1) * g
        new_v = self.beta2 * v._data + (1. - self.beta2) * g * g
        m_t_prime = new_m / (1. - m_schedule_next)
        v_t_prime = new_v / (1. - self.beta2 ** t)
        m_t_bar = (1. - momentum_t) * grad_prime + \
            momentum_t_1 * m_t_prime
        m._set_data(new_m)
        v._set_data(new_v)
        weight._set_data(
            weight._data - lr * m_t_bar
            / (jnp.sqrt(v_t_prime) + self.epsilon))


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        _oo.adagrad_update(weight, grad, state, lr=lr,
                           epsilon=self.float_stable_eps, wd=wd,
                           rescale_grad=self.rescale_grad,
                           clip_gradient=self.clip_gradient or -1.0)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (_zeros_like(weight), _zeros_like(weight),
                    _zeros_like(weight))
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(lr=lr, gamma1=self.gamma1, epsilon=self.epsilon, wd=wd,
                  rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0,
                  clip_weights=self.clip_weights or -1.0)
        if self.centered:
            n, g_mean, delta = state
            _oo.rmspropalex_update(weight, grad, n, g_mean, delta,
                                   gamma2=self.gamma2, **kw)
        else:
            _oo.rmsprop_update(weight, grad, state, **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        _oo.adadelta_update(weight, grad, acc_g, acc_delta, rho=self.rho,
                            epsilon=self.epsilon, wd=wd,
                            rescale_grad=self.rescale_grad,
                            clip_gradient=self.clip_gradient or -1.0)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))  # z, n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        _oo.ftrl_update(weight, grad, z, n, lr=lr, lamda1=self.lamda1,
                        beta=self.beta, wd=wd,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=self.clip_gradient or -1.0)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0)
        if state is not None:
            _oo.signum_update(weight, grad, state, momentum=self.momentum,
                              wd_lh=self.wd_lh, **kw)
        else:
            _oo.signsgd_update(weight, grad, **kw)


SignSGD = Signum


@register
class LAMB(Optimizer):
    """reference: LAMB (1.6+) — layerwise trust-ratio adaptive Adam."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (_zeros_like(weight, _np.float32),
                _zeros_like(weight, _np.float32))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        g = _oo.lamb_update_phase1(
            weight, grad, mean, var, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, t=t, bias_correction=self.bias_correction,
            wd=wd, rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0)
        r1 = NDArray(jnp.linalg.norm(weight._data.ravel()), ctx=weight.ctx)
        r2 = NDArray(jnp.linalg.norm(g._data.ravel()), ctx=weight.ctx)
        _oo.lamb_update_phase2(weight, g, r1, r2, lr=lr,
                               lower_bound=self.lower_bound or -1.0,
                               upper_bound=self.upper_bound or -1.0)


@register
class SGLD(Optimizer):
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        _oo.sgld_update(weight, grad, lr=lr, wd=wd,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=self.clip_gradient or -1.0)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: DCASGD).  Kept for API
    parity; delay compensation is moot in SPMD execution."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_zeros_like(weight), weight.copy())

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, prev = state
        d = -lr * (g + wd * weight._data + self.lamda * g * g *
                   (weight._data - prev._data))
        if mom is not None:
            new_mom = self.momentum * mom._data + d
            mom._set_data(new_mom)
            d = new_mom
        prev._set_data(weight._data)
        weight._set_data(weight._data + d)


@register
class FTML(Optimizer):
    """Follow The Moving Leader (reference: optimizer.FTML /
    src/operator/optimizer_op.cc ftml_update; Zheng & Kwok 2017)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight),
                _zeros_like(weight))           # d, v, z

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._data
        v_t = self.beta2 * v._data + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v_t / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d._data
        z_t = self.beta1 * z._data + (1 - self.beta1) * g \
            - sigma * weight._data
        d._set_data(d_t)
        v._set_data(v_t)
        z._set_data(z_t)
        weight._set_data(-z_t / d_t)


@register
class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling SGD (reference: optimizer.LARS,
    1.6+; You et al. 2017).  Per-layer trust ratio
    eta*||w|| / (||g|| + wd*||w||) scales the learning rate before a
    momentum-SGD step."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        w_norm = jnp.linalg.norm(weight._data.ravel())
        g_norm = jnp.linalg.norm(g.ravel())
        ratio = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
            1.0)
        step = (lr * ratio) * (g + wd * weight._data)
        if state is not None:
            m_t = self.momentum * state._data + step
            state._set_data(m_t)
            weight._set_data(weight._data - m_t)
        else:
            weight._set_data(weight._data - step)


@register
class GroupAdaGrad(Optimizer):
    """AdaGrad with ONE accumulated scalar per row (reference:
    contrib.optimizer GroupAdaGrad — the GluonNLP sparse-embedding
    optimizer).  history[i] += mean(grad[i]^2); w[i] -= lr * g[i] /
    (sqrt(history[i]) + eps)."""

    def __init__(self, learning_rate=0.01, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        import jax.numpy as jnp
        return NDArray(jnp.zeros((weight.shape[0],) + (1,)
                                 * (len(weight.shape) - 1),
                                 weight._data.dtype), ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr = self._get_lr(index)
        if self._get_wd(index):
            raise MXNetError("GroupAdaGrad does not support weight decay "
                             "(reference parity)")
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        axes = tuple(range(1, g.ndim))
        hist = state._data + jnp.mean(g * g, axis=axes, keepdims=True)
        state._set_data(hist)
        # epsilon INSIDE the sqrt (reference kernel + our adagrad_update)
        weight._set_data(
            weight._data - lr * g / jnp.sqrt(hist + self.epsilon))


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference: contrib.adamw /
    mx.optimizer AdamW in later 1.x; Loshchilov & Hutter 2019)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (_zeros_like(weight), _zeros_like(weight))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        g = _oo._as_dense_grad(grad)._data * self.rescale_grad
        if self.clip_gradient:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        m_t = self.beta1 * mean._data + (1 - self.beta1) * g
        v_t = self.beta2 * var._data + (1 - self.beta2) * g * g
        m_hat = m_t / (1 - self.beta1 ** t)
        v_hat = v_t / (1 - self.beta2 ** t)
        mean._set_data(m_t)
        var._set_data(v_t)
        weight._set_data(
            weight._data - lr * (m_hat / (jnp.sqrt(v_hat) + self.epsilon)
                                 + wd * weight._data))


@register
class Test(Optimizer):
    """reference: Test optimizer (w -= lr*grad, used in unit tests)."""

    def create_state(self, index, weight):
        return _zeros_like(weight)

    def update(self, index, weight, grad, state):
        weight._set_data(
            weight._data - self.lr * _oo._as_dense_grad(grad)._data * self.rescale_grad)


class Updater:
    """KVStore server-side updater wrapper (reference: get_updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)

    def set_states(self, states):
        import pickle
        obj = pickle.loads(states)
        if isinstance(obj, dict) and obj.get("loop") == 1:
            # a parallel.CompiledLoop blob: installing it as per-index
            # updater states would silently resume with fresh optimizer
            # state — the mirror of CompiledLoop.set_states rejecting
            # foreign blobs
            raise MXNetError(
                "checkpoint trainer states were saved from a "
                "parallel.CompiledLoop — restore with trainer=<the "
                "CompiledLoop>, not an eager Trainer")
        if isinstance(obj, tuple):
            self.states, self.optimizer = obj
        else:
            self.states = obj


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
