"""Pure functional optimizer update cores.

One set of update-rule formulas shared by all three execution tiers:

* the eager per-param ops (``ndarray/optimizer_ops.py`` — reference
  kernels src/operator/optimizer_op.cc),
* the fused whole-tree Trainer step (``optimizer/fused.py`` — one
  donated jit dispatch per ``Trainer.step``),
* the compiled SPMD optimizers (``parallel/optim.py``).

Every core is a pure function over raw ``jnp`` arrays; scalars may be
Python floats (baked into the trace) or traced 0-d arrays (per-step /
per-param hyperparameters) — the arithmetic and its evaluation order are
IDENTICAL either way, which is what makes the fused path bit-compatible
with the per-param loop it replaces.  Keep the expressions in lockstep
with the reference kernels; parity is asserted in
tests/test_optimizer.py (vs hand NumPy) and tests/test_fused_optimizer.py
(fused vs loop).
"""
from __future__ import annotations

__all__ = ["prep_grad", "sgd", "sgd_momentum", "nag_momentum", "moments",
           "adam", "adamw", "rmsprop", "adagrad"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def prep_grad(g, rescale_grad=None, clip_gradient=None, wd=None, w=None):
    """rescale → clip → fold wd*w into the gradient (reference: the
    common prologue of every optimizer kernel).  ``None`` skips a stage —
    the callers decide statically (at trace time) which stages apply, so
    a zero wd produces the exact same graph as the reference's
    ``if wd`` branch."""
    jnp = _jnp()
    if rescale_grad is not None:
        g = g * rescale_grad
    if clip_gradient is not None:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd is not None and w is not None:
        g = g + wd * w
    return g


def sgd(w, g, lr):
    """reference: sgd_update (g already prepped, wd folded)."""
    return w - lr * g


def sgd_momentum(w, g, m, lr, momentum):
    """reference: sgd_mom_update → (new_w, new_mom)."""
    new_m = momentum * m - lr * g
    return w + new_m, new_m


def nag_momentum(w, g, m, lr, momentum):
    """reference: nag_mom_update → (new_w, new_mom)."""
    new_m = momentum * m + g
    return w - lr * (g + momentum * new_m), new_m


def moments(m, v, g, beta1, beta2):
    """Adam-family first/second moment EMA → (new_m, new_v)."""
    return beta1 * m + (1 - beta1) * g, beta2 * v + (1 - beta2) * g * g


def adam(w, g, m, v, lr, beta1, beta2, epsilon):
    """reference: adam_update — ``lr`` arrives PRE-SCALED by
    sqrt(1-beta2^t)/(1-beta1^t) (the Python Adam class folds the bias
    correction into lr); wd is folded into g by prep_grad.
    → (new_w, new_m, new_v)."""
    jnp = _jnp()
    new_m, new_v = moments(m, v, g, beta1, beta2)
    return w - lr * new_m / (jnp.sqrt(new_v) + epsilon), new_m, new_v


def adamw(w, g, m, v, lr, wd, beta1, beta2, epsilon, coef1, coef2):
    """reference: AdamW (decoupled weight decay).  ``coef1``/``coef2``
    are the bias-correction denominators 1-beta1^t / 1-beta2^t, passed
    in so a traced step count and the eager Python-float path share one
    formula.  → (new_w, new_m, new_v)."""
    jnp = _jnp()
    new_m, new_v = moments(m, v, g, beta1, beta2)
    m_hat = new_m / coef1
    v_hat = new_v / coef2
    return (w - lr * (m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * w),
            new_m, new_v)


def rmsprop(w, g, n, lr, gamma1, epsilon):
    """reference: rmsprop_update (non-centered; epsilon inside the
    sqrt); wd folded into g by prep_grad.  → (new_w, new_n)."""
    jnp = _jnp()
    new_n = (1 - gamma1) * g * g + gamma1 * n
    return w - lr * g / jnp.sqrt(new_n + epsilon), new_n


def adagrad(w, g, h, lr, epsilon, wd):
    """reference: adagrad_update — wd applies decoupled (outside the
    adaptive term), epsilon inside the sqrt.  → (new_w, new_h)."""
    jnp = _jnp()
    new_h = h + g * g
    return w - lr * (g / jnp.sqrt(new_h + epsilon) + wd * w), new_h
