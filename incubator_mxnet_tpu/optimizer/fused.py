"""Fused whole-tree optimizer step: ONE donated jit dispatch per
``Trainer.step``.

The eager Gluon update loop pays one dispatch per parameter per step —
the dispatch-overhead wall PyGraph (arXiv:2503.19779) attacks with graph
capture, and the dominant step-time term on TPU once compute is sharded
(arXiv:2004.13336).  :class:`FusedUpdater` gathers every
``(weight, grad, state)`` triple into one pytree and applies the update
rule — the same pure cores the per-param ops and the SPMD path use
(``optimizer/cores.py``) — as a single ``jax.jit`` call with donated
buffers, so XLA fuses hundreds of tiny updates into one executable and
reuses the parameter memory in place.

What is folded inside the compiled program:

* grad rescale (traced scalar — changing batch size does NOT recompile),
* ``clip_gradient`` (traced scalar when enabled),
* per-param lr / wd multipliers (traced ``(n,)`` vectors — lr schedules
  and ``set_learning_rate`` do not recompile),
* multi-precision fp16 master weights (fp32 master in the state, fp16
  view written back, exactly like ``update_multi_precision``),
* the ``skip_nonfinite`` guard: the all-finite check
  (``amp.all_finite_flag`` — the SAME reduction the eager guard uses)
  becomes a fused reduction whose result gates every output through
  ``jnp.where``, so the guard costs no blocking host sync per step;
  skipped-step counting moves to an async readback
  (``Trainer.sync_nonfinite_guard`` forces it).

Compiled programs are cached by static configuration — (rule, baked
hyperparameters, multi-precision/wd patterns, clip/guard flags) — and by
tree structure/shapes/dtypes (jax's own jit cache).  Changing a baked
hyperparameter (momentum, betas, epsilon) recompiles; changing lr, wd,
rescale, or clip values does not.

Numerics: bit-compatible with the per-param loop it replaces — the cores
keep expression and evaluation order identical, traced scalars are cast
to each param's compute dtype (matching the weak-typed Python floats the
eager ops receive, so fp16/bf16 params without master weights stay in
their own dtype), and host-side bookkeeping (update counts, Adam
bias-corrected lr in Python doubles) mirrors the eager classes —
asserted by tests/test_fused_optimizer.py.  Documented divergences:
update counts advance even on a guard-skipped step (the flag is not
known at dispatch time; the eager guard skips the whole update including
the count), and low-precision params may differ from the loop by ~1 ulp
because the single fused program keeps elementwise intermediates in f32
where the op-by-op dispatch rounds at every op boundary.
"""
from __future__ import annotations

import math
import warnings
from typing import Dict, Optional, Tuple

from .. import health as _health
from .. import telemetry as _telemetry
from ..ndarray.ndarray import NDArray
from .optimizer import SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, LAMB, \
    Updater

__all__ = ["FusedUpdater", "functional_twin"]

# exact-type table: NAG subclasses SGD but has a different rule; LARS /
# Signum / centered-RMSProp etc. are absent → per-param fallback
_RULES = {SGD: "sgd", NAG: "nag", Adam: "adam", AdamW: "adamw",
          RMSProp: "rmsprop", AdaGrad: "adagrad", LAMB: "lamb"}

# rules whose eager kernel folds wd into the gradient (prep_grad) only
# when wd != 0; adamw/adagrad/lamb apply wd decoupled, unconditionally
_FOLD_WD = ("sgd", "nag", "adam", "rmsprop")

# rules whose update is purely elementwise given the prepped grad — the
# ZeRO-1 flat-shard envelope.  LAMB's per-tensor trust ratio straddles
# shard boundaries, so it runs fused but unsharded.
_ZERO1_RULES = ("sgd", "nag", "adam", "adamw", "rmsprop", "adagrad")


def functional_twin(optimizer):
    """A ``parallel.optim`` FunctionalOptimizer matching an eager
    optimizer instance — the bridge CompiledLoop / SPMDTrainer use to
    take over a model configured for the eager ``Trainer``.

    Raises :class:`MXNetError` when the eager configuration carries
    host-side per-step behavior a pure traced update cannot reproduce
    (lr_scheduler callbacks, centered / clip_weights RMSProp, LAMB
    bounds / bias_correction=False) — callers should surface that and
    stay on the per-step path rather than silently change numerics.
    ``rescale_grad`` and ``clip_gradient`` thread through as baked
    scalars exactly like the fused eager path.  Note adam's bias
    correction rounds differently between the tiers (host doubles folded
    into lr here vs. traced f32 in the functional core), a documented
    ~1-ulp-class divergence; sgd/nag are bit-exact.
    """
    from ..base import MXNetError
    from ..parallel import optim as _fopt   # lazy: avoids import cycle

    rule = _RULES.get(type(optimizer))
    if rule is None:
        raise MXNetError(
            f"no functional twin for {type(optimizer).__name__} — "
            "pass a parallel.optim optimizer explicitly")
    if getattr(optimizer, "lr_scheduler", None) is not None:
        raise MXNetError(
            "functional_twin cannot capture a host-side lr_scheduler — "
            "pass lr_schedule= (a traced step -> lr callable) to the "
            "functional optimizer instead")
    kw = dict(learning_rate=optimizer.lr, wd=optimizer.wd,
              rescale_grad=float(optimizer.rescale_grad),
              clip_gradient=optimizer.clip_gradient or None)
    if rule in ("sgd", "nag"):
        kw["momentum"] = optimizer.momentum
    elif rule in ("adam", "adamw"):
        kw.update(beta1=optimizer.beta1, beta2=optimizer.beta2,
                  epsilon=optimizer.epsilon)
    elif rule == "lamb":
        if optimizer.lower_bound is not None or \
                optimizer.upper_bound is not None or \
                not optimizer.bias_correction:
            raise MXNetError(
                "functional_twin: LAMB trust-ratio bounds / "
                "bias_correction=False are outside the functional "
                "envelope")
        kw.update(beta1=optimizer.beta1, beta2=optimizer.beta2,
                  epsilon=optimizer.epsilon)
    elif rule == "rmsprop":
        if optimizer.centered or optimizer.clip_weights:
            raise MXNetError(
                "functional_twin: centered / clip_weights RMSProp is "
                "outside the functional envelope")
        kw.update(gamma1=optimizer.gamma1, epsilon=optimizer.epsilon)
    else:                                   # adagrad
        kw["epsilon"] = optimizer.float_stable_eps
    return _fopt.create(rule, **kw)


def _raw_state(s):
    """updater.states[i] structure (NDArrays / tuples / None) → raw jax
    arrays with the same structure."""
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s._data
    return tuple(_raw_state(x) for x in s)


def _writeback_state(s, new):
    """Write raw output arrays back into the (stable) NDArray wrappers —
    save/load_states and a later fallback to the loop keep working."""
    if s is None:
        return
    if isinstance(s, NDArray):
        s._set_data(new)
        return
    for a, b in zip(s, new):
        _writeback_state(a, b)


def _seg_state_flats(seg, raw_by_pos, n_leaves):
    """Flatten one segment's per-leaf optimizer states into flat padded
    buffers, slot by slot (a "slot" is one leaf of the per-param state
    structure — e.g. adam has two, m and v; multi-precision prepends the
    fp32 master weight).  All leaves in a segment share rule and
    mp-ness, so the slot structure is uniform.  Returns
    ``(slot_structured_flats, treedef)``."""
    import jax
    from ..parallel import zero1 as _z1

    treedef = jax.tree.structure(raw_by_pos[seg.idx[0]])
    slots = [jax.tree.leaves(raw_by_pos[k]) for k in seg.idx]
    flats = []
    for j in range(treedef.num_leaves):
        leaves = [None] * n_leaves
        for pos, k in enumerate(seg.idx):
            leaves[k] = slots[pos][j]
        flats.append(_z1.flatten_segment(seg, leaves,
                                         dtype=slots[0][j].dtype))
    return jax.tree.unflatten(treedef, flats), treedef


class FusedUpdater:
    """Whole-tree fused twin of :class:`optimizer.Updater`.

    Shares the wrapped Updater's ``states`` dict and ``optimizer``
    (re-read every step, so ``set_states`` / ``load_states`` swapping
    the optimizer keeps working), creates missing states exactly like
    the eager path, and leaves the per-param loop usable at any time —
    :meth:`step` returns ``(False, None)`` whenever the current
    optimizer or parameter set is outside the fused envelope.
    """

    def __init__(self, updater: Updater, zero1: bool = False):
        self._updater = updater
        self._cache: Dict[tuple, object] = {}
        # ZeRO-1 (arXiv:2004.13336): shard the flat update + optimizer
        # state across the local devices.  Pointless on one device —
        # silently stay on the replicated fused path there.
        self._z_mesh = None
        if zero1:
            import jax
            if len(jax.local_devices()) > 1:
                from ..parallel.mesh import make_mesh
                self._z_mesh = make_mesh(
                    {"data": len(jax.local_devices())})
        self._z_key = None          # config key the flat cache matches
        self._z_spec = None         # parallel.zero1.ShardSpec
        self._z_state = None        # per-segment flat sharded state
        self._z_defs = None         # per-segment state treedefs
        self._z_params = None       # [(param_index, weight NDArray)]
        # health plane (health.py): per-leaf stats as extra outputs of
        # the fused dispatch, drained asynchronously.  Monitor created
        # lazily on the first step (leaf names come from updatable).
        self._health = None

    # -- per-step host side --------------------------------------------
    # mxtpu-lint: hot-path
    def step(self, updatable, guard: bool):
        """Apply one fused update to ``updatable`` (list of
        ``(index, Parameter)``).

        Returns ``(handled, flag)``: ``handled`` False means the caller
        must run the per-param loop instead; ``flag`` is the device-side
        all-finite bool (only when ``guard``) for async readback."""
        import numpy as np
        import jax

        opt = self._updater.optimizer
        rule = _RULES.get(type(opt))
        if rule is None:
            # every (False, None) return materializes the zero1 flat
            # shards first (no-op when inactive): the per-param loop the
            # caller falls back to reads updater.states
            self._flush_zero1()
            return False, None
        if rule == "rmsprop" and (opt.centered or opt.clip_weights):
            self._flush_zero1()
            return False, None
        n = len(updatable)
        if n == 0:
            return True, None

        ws_nd, gs_nd = [], []
        for _, p in updatable:
            if p.stype != "default" or \
                    getattr(p, "_grad_stype", "default") != "default":
                self._flush_zero1()
                return False, None
            ws_nd.append(p.data())
            gs_nd.append(p.grad())

        # ZeRO-1 handles the elementwise rules only (LAMB's trust ratio
        # straddles flat-shard boundaries); anything else falls back to
        # the replicated fused path — materialize first so the eager
        # states dict is the source of truth again
        use_z = self._z_mesh is not None and rule in _ZERO1_RULES
        if self._z_state is not None and not use_z:
            self._flush_zero1()

        states = self._updater.states
        ws = tuple(w._data for w in ws_nd)
        gs = tuple(g._data for g in gs_nd)
        if use_z:
            sts = None
            donated = list(ws) + (list(gs) if guard else [])
        else:
            for (i, _), w in zip(updatable, ws_nd):
                if i not in states:
                    states[i] = opt.create_state_multi_precision(i, w)
            sts = tuple(_raw_state(states[i]) for i, _ in updatable)
            donated = list(ws) + jax.tree_util.tree_leaves(sts) + \
                (list(gs) if guard else [])
        if len({id(x) for x in donated}) != len(donated):
            # aliased buffers cannot be donated — bail BEFORE touching
            # update counts / lr bookkeeping, so the per-param fallback
            # (which advances them itself) sees them exactly once
            self._flush_zero1()
            return False, None

        # host bookkeeping in eager order: every param's count advances
        # before any lr is read, so a shared lr_scheduler sees the same
        # num_update for the whole tree (what the per-param loop
        # converges to after the first param)
        for i, _ in updatable:
            opt._update_count(i)
        mp_pattern, wd_pattern = [], []
        lrs = np.empty(n, np.float32)
        wds = np.empty(n, np.float32)
        for k, (i, p) in enumerate(updatable):
            lr, wd = opt._get_lr(i), opt._get_wd(i)
            if rule == "adam":
                # bias correction folds into lr in Python doubles, then
                # rounds once — the same bits the eager Adam class feeds
                # adam_update
                t = opt._index_update_count[i]
                lr *= math.sqrt(1. - opt.beta2 ** t) / (1. - opt.beta1 ** t)
            lrs[k] = lr
            wds[k] = wd
            wd_pattern.append(bool(wd))
            mp_pattern.append(bool(opt.multi_precision
                                   and ws_nd[k].dtype == np.float16))
        if rule == "adamw" or (rule == "lamb" and opt.bias_correction):
            counts = [opt._index_update_count[i] for i, _ in updatable]
            extras = (np.array([1. - opt.beta1 ** t for t in counts],
                               np.float32),
                      np.array([1. - opt.beta2 ** t for t in counts],
                               np.float32))
        else:
            extras = ()

        health_on = _health.enabled()
        if health_on and self._health is None:
            self._health = _health.HealthMonitor(
                [p.name for _, p in updatable], src="fused")

        clip = opt.clip_gradient
        clip_on = bool(clip and clip > 0)
        if rule in ("sgd", "nag"):
            baked = (opt.momentum,)
        elif rule in ("adam", "adamw"):
            baked = (opt.beta1, opt.beta2, opt.epsilon)
        elif rule == "lamb":
            baked = (opt.beta1, opt.beta2, opt.epsilon,
                     bool(opt.bias_correction),
                     float(opt.lower_bound or -1.0),
                     float(opt.upper_bound or -1.0))
        elif rule == "rmsprop":
            baked = (opt.gamma1, opt.epsilon)
        else:
            baked = (opt.float_stable_eps,)

        if use_z:
            return self._step_zero1(
                updatable, ws_nd, gs_nd, ws, gs, lrs, wds, extras, rule,
                baked, tuple(mp_pattern), tuple(wd_pattern), clip_on,
                guard, opt, health_on)

        key = (rule, n, baked, tuple(mp_pattern), tuple(wd_pattern),
               clip_on, guard, health_on)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._cache[key] = self._build(key)
        # donation is best-effort: CPU jax has no buffer donation —
        # harmless, the dispatch win stands — and the per-call warning
        # is pure noise.  Scoped here so user jax code keeps seeing it.
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            out = fn(ws, gs, sts, lrs, wds, extras,
                     np.float32(opt.rescale_grad),
                     np.float32(clip if clip_on else 0.0))
        if health_on:
            new_ws, new_sts, new_gs, flag, hstats = out
            # the fused path never sees the loss; the record carries
            # grad/update stats only (loss rides the spmd/loop planes)
            self._health.submit(opt.num_update - 1, 1, hstats)
        else:
            new_ws, new_sts, new_gs, flag = out

        for k, (i, _) in enumerate(updatable):
            ws_nd[k]._set_data(new_ws[k])
            _writeback_state(states[i], new_sts[k])
            if new_gs is not None:
                gs_nd[k]._set_data(new_gs[k])
        c = _telemetry.counter(
            "mxtpu_optimizer_fused_updates",
            "whole-tree fused optimizer dispatches "
            "(one jit call updating every parameter)")
        c.inc(site="fused_update")
        _telemetry.gauge(
            "mxtpu_optimizer_dispatches_per_step",
            "optimizer-update dispatches in the last trainer step "
            "(1 = fused; num_params = per-param loop)").set(1)
        from ..parallel import zero1 as _z1
        _telemetry.gauge(
            "mxtpu_optimizer_state_bytes",
            "optimizer-state bytes ONE replica materializes "
            "(replicated state: the full tree; zero1: its 1/N shard)"
        ).set(_z1.per_replica_state_bytes(
            tuple(_raw_state(states[i]) for i, _ in updatable)))
        return True, flag

    # -- ZeRO-1 flat-sharded path --------------------------------------
    def flush_states(self):
        """Materialize the flat sharded optimizer state back into the
        wrapped Updater's per-param ``states`` dict (checkpoint time /
        fallback to an out-of-envelope rule).  No-op when ZeRO-1 is off
        or not yet engaged."""
        self._flush_zero1()

    def invalidate(self):
        """Drop the flat sharded state WITHOUT materializing — the
        caller replaced ``updater.states`` wholesale (``set_states`` /
        ``load_states``), making the eager dict the truth again."""
        self._z_state = None
        self._z_key = None
        self._z_spec = None
        self._z_defs = None
        self._z_params = None

    def _flush_zero1(self):
        if self._z_state is None:
            return
        import numpy as np
        import jax
        import jax.numpy as jnp
        states = self._updater.states
        opt = self._updater.optimizer
        spec = self._z_spec
        for i, w_nd in self._z_params:
            if i not in states:
                states[i] = opt.create_state_multi_precision(i, w_nd)
        from ..parallel import zero1 as _z1
        for seg, st_seg, treedef in zip(spec.segments, self._z_state,
                                        self._z_defs):
            flats = [np.asarray(x) for x in jax.tree.leaves(st_seg)]
            per_leaf = {k: [] for k in seg.idx}
            for flat in flats:
                for k, arr in _z1.unflatten_segment(seg, flat):
                    per_leaf[k].append(jnp.asarray(arr))
            for k in seg.idx:
                raw = jax.tree.unflatten(treedef, per_leaf[k])
                _writeback_state(states[self._z_params[k][0]], raw)
        self.invalidate()

    def _step_zero1(self, updatable, ws_nd, gs_nd, ws, gs, lrs, wds,
                    extras, rule, baked, mp_pattern, wd_pattern, clip_on,
                    guard, opt, health_on=False):
        import numpy as np
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from ..parallel import zero1 as _z1

        n = len(updatable)
        shapes = tuple(tuple(map(int, w.shape)) for w in ws)
        wdts = tuple(np.dtype(w.dtype).str for w in ws)
        key = ("z1", rule, n, baked, mp_pattern, wd_pattern, clip_on,
               guard, shapes, wdts, health_on)
        if self._z_state is not None and self._z_key != key:
            # param set / patterns changed under us — re-partition from
            # the materialized truth
            self._flush_zero1()
        shard = NamedSharding(self._z_mesh, PartitionSpec("data"))
        repl = NamedSharding(self._z_mesh, PartitionSpec())
        if self._z_state is None:
            states = self._updater.states
            for (i, _), w in zip(updatable, ws_nd):
                if i not in states:
                    states[i] = opt.create_state_multi_precision(i, w)
            seg_keys = [(wdts[k], mp_pattern[k],
                         rule in _FOLD_WD and wd_pattern[k])
                        for k in range(n)]
            spec = _z1.build_shard_spec(
                ws, int(self._z_mesh.shape["data"]), keys=seg_keys)
            raw = [_raw_state(states[i]) for i, _ in updatable]
            z_state, z_defs = [], []
            for seg in spec.segments:
                st, treedef = _seg_state_flats(seg, raw, n)
                st = jax.tree.map(lambda v: jax.device_put(v, shard), st)
                z_state.append(st)
                z_defs.append(treedef)
            self._z_state = tuple(z_state)
            self._z_defs = tuple(z_defs)
            self._z_spec = spec
            self._z_key = key
            self._z_params = [(i, w)
                              for (i, _), w in zip(updatable, ws_nd)]
            # the flat shards are now the only copy — per-replica memory
            # actually drops N×
            for i, _ in updatable:
                states.pop(i, None)
            _telemetry.gauge(
                "mxtpu_zero1_allgather_bytes",
                "per-step per-replica inbound all-gather volume the "
                "zero1 weight-update sharding adds").set(
                _z1.zero1_allgather_bytes(spec))
        fn = self._cache.get(key)
        if fn is None:
            fn = self._cache[key] = self._build_zero1(key)
        devs = [next(iter(w.devices())) for w in ws]
        ws_m = tuple(jax.device_put(w, repl) for w in ws)
        gs_m = tuple(jax.device_put(g, repl) for g in gs)
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            out = fn(ws_m, gs_m, self._z_state, lrs, wds, extras,
                     np.float32(opt.rescale_grad),
                     np.float32(opt.clip_gradient if clip_on else 0.0))
        if health_on:
            new_ws, new_z, new_gs, flag, hstats = out
            self._health.submit(opt.num_update - 1, 1, hstats)
        else:
            new_ws, new_z, new_gs, flag = out
        self._z_state = new_z
        # weights return to their eager (single-device) homes so the
        # next forward pass is undisturbed; these copies are plain
        # transfers, not dispatches — the update stayed ONE jit call
        for k in range(n):
            ws_nd[k]._set_data(jax.device_put(new_ws[k], devs[k]))
            if new_gs is not None:
                gs_nd[k]._set_data(jax.device_put(new_gs[k], devs[k]))
        _telemetry.counter(
            "mxtpu_optimizer_fused_updates",
            "whole-tree fused optimizer dispatches "
            "(one jit call updating every parameter)").inc(
            site="zero1_update")
        _telemetry.gauge(
            "mxtpu_optimizer_dispatches_per_step",
            "optimizer-update dispatches in the last trainer step "
            "(1 = fused; num_params = per-param loop)").set(1)
        _telemetry.gauge(
            "mxtpu_optimizer_state_bytes",
            "optimizer-state bytes ONE replica materializes "
            "(replicated state: the full tree; zero1: its 1/N shard)"
        ).set(_z1.per_replica_state_bytes(self._z_state))
        return True, flag

    def _build_zero1(self, key):
        import jax
        import jax.numpy as jnp
        from jax.lax import with_sharding_constraint as wsc
        from jax.sharding import NamedSharding, PartitionSpec
        from . import cores
        from ..contrib.amp.loss_scaler import all_finite_flag
        from ..parallel import zero1 as _z1

        (_, rule, n, baked, mp_pattern, wd_pattern, clip_on, guard,
         shapes, wdts, health_on) = key
        spec, treedefs = self._z_spec, self._z_defs
        shard = NamedSharding(self._z_mesh, PartitionSpec("data"))
        repl = NamedSharding(self._z_mesh, PartitionSpec())

        def fn(ws, gs, zstates, lrs, wds, extras, rescale, clip):
            allfin = all_finite_flag(gs) if guard else None
            new_ws = [None] * n
            new_z = []
            for seg, st_seg, treedef in zip(spec.segments, zstates,
                                            treedefs):
                _, mp, wdfold = seg.key
                cdt = jnp.float32 if mp else seg.dtype
                leaves = jax.tree.leaves(st_seg)
                g_flat = wsc(_z1.flatten_segment(seg, gs, dtype=cdt),
                             shard)
                if mp:
                    tw, inner = leaves[0], leaves[1:]
                else:
                    tw = wsc(_z1.flatten_segment(seg, ws), shard)
                    inner = leaves
                lr = _z1.expand_per_leaf(seg, lrs, dtype=cdt)
                wd = _z1.expand_per_leaf(seg, wds, dtype=cdt)
                gp = cores.prep_grad(
                    g_flat, rescale.astype(cdt),
                    clip.astype(cdt) if clip_on else None,
                    wd if wdfold else None, tw)
                if rule in ("sgd", "nag"):
                    momentum, = baked
                    if not inner:
                        nw, ninner = cores.sgd(tw, gp, lr), []
                    elif rule == "sgd":
                        nw, nm = cores.sgd_momentum(tw, gp, inner[0],
                                                    lr, momentum)
                        ninner = [nm]
                    else:
                        nw, nm = cores.nag_momentum(tw, gp, inner[0],
                                                    lr, momentum)
                        ninner = [nm]
                elif rule == "adam":
                    b1, b2, eps = baked
                    nw, nm, nv = cores.adam(tw, gp, inner[0], inner[1],
                                            lr, b1, b2, eps)
                    ninner = [nm, nv]
                elif rule == "adamw":
                    b1, b2, eps = baked
                    coef1 = _z1.expand_per_leaf(seg, extras[0],
                                                dtype=cdt)
                    coef2 = _z1.expand_per_leaf(seg, extras[1],
                                                dtype=cdt)
                    nw, nm, nv = cores.adamw(tw, gp, inner[0], inner[1],
                                             lr, wd, b1, b2, eps,
                                             coef1, coef2)
                    ninner = [nm, nv]
                elif rule == "rmsprop":
                    g1, eps = baked
                    nw, nn = cores.rmsprop(tw, gp, inner[0], lr, g1,
                                           eps)
                    ninner = [nn]
                else:
                    eps, = baked
                    nw, nh = cores.adagrad(tw, gp, inner[0], lr, eps,
                                           wd)
                    ninner = [nh]
                if guard:
                    nw = jnp.where(allfin, nw, tw)
                    ninner = [jnp.where(allfin, a, b)
                              for a, b in zip(ninner, inner)]
                nleaves = ([nw] + ninner) if mp else ninner
                new_z.append(jax.tree.unflatten(
                    treedef, [wsc(x, shard) for x in nleaves]))
                # replicating the updated flat weights IS the
                # all-gather — still inside this one donated dispatch.
                # The barrier keeps the update arithmetic OUT of the
                # all-gather's fusion cluster: fused into the gather,
                # XLA re-contracts the multiply-add chain (different
                # FMA placement) and the result drifts 1-2 ulp off the
                # unsharded program — bit parity requires the kernel
                # boundary here.
                out_w = wsc(jax.lax.optimization_barrier(
                    nw.astype(seg.dtype)), repl)
                for k, arr in _z1.unflatten_segment(seg, out_w):
                    new_ws[k] = arr
            new_ws, new_z = tuple(new_ws), tuple(new_z)
            # stats over the FULL (replicated) grads/weights — the
            # all-gathered new_ws is already final here, so the zero1
            # and replicated planes report identical leaf attribution
            h = _health.train_step_health(gs, ws, new_ws) \
                if health_on else None
            if not guard:
                return (new_ws, new_z, None, None) \
                    + ((h,) if health_on else ())
            return (new_ws, new_z,
                    tuple(jnp.where(allfin, g, jnp.zeros_like(g))
                          for g in gs),
                    allfin) + ((h,) if health_on else ())

        jitted = jax.jit(fn, donate_argnums=(0, 1, 2) if guard else (0, 2))
        return _telemetry.instrument_jit("zero1_update", jitted)

    # -- compiled side -------------------------------------------------
    def _build(self, key):
        import jax
        import jax.numpy as jnp
        from . import cores
        from ..contrib.amp.loss_scaler import all_finite_flag

        rule, n, baked, mp_pattern, wd_pattern, clip_on, guard, \
            health_on = key

        def fn(ws, gs, states, lrs, wds, extras, rescale, clip):
            # guard decides on the RAW grads (pre-rescale), exactly like
            # the eager _grads_nonfinite → amp.all_finite check
            allfin = all_finite_flag(gs) if guard else None
            new_ws, new_sts = [], []
            for k in range(n):
                w, g, st = ws[k], gs[k], states[k]
                if mp_pattern[k]:
                    w32, inner = st
                    tw, tst, gk = w32, inner, g.astype(jnp.float32)
                else:
                    tw, tst, gk = w, st, g
                # the eager ops take Python-float hyperparameters, which
                # jax weak-types to the array dtype — fp16/bf16 params
                # without master weights compute (and stay) in their own
                # dtype.  The traced scalars here are strongly-typed
                # f32, so cast them to the compute dtype (a no-op for
                # f32 weights and fp32 master weights) to keep the
                # arithmetic and output dtypes identical to the loop.
                cdt = tw.dtype
                lr, wd = lrs[k].astype(cdt), wds[k].astype(cdt)
                gp = cores.prep_grad(
                    gk, rescale.astype(cdt),
                    clip.astype(cdt) if clip_on else None,
                    wd if (rule in _FOLD_WD and wd_pattern[k]) else None,
                    tw)
                if rule in ("sgd", "nag"):
                    momentum, = baked
                    if tst is None:
                        nw, nst = cores.sgd(tw, gp, lr), None
                    elif rule == "sgd":
                        nw, nst = cores.sgd_momentum(tw, gp, tst, lr,
                                                     momentum)
                    else:
                        nw, nst = cores.nag_momentum(tw, gp, tst, lr,
                                                     momentum)
                elif rule == "adam":
                    b1, b2, eps = baked
                    nw, nm, nv = cores.adam(tw, gp, tst[0], tst[1], lr,
                                            b1, b2, eps)
                    nst = (nm, nv)
                elif rule == "adamw":
                    b1, b2, eps = baked
                    coef1s, coef2s = extras
                    nw, nm, nv = cores.adamw(tw, gp, tst[0], tst[1], lr,
                                             wd, b1, b2, eps,
                                             coef1s[k].astype(cdt),
                                             coef2s[k].astype(cdt))
                    nst = (nm, nv)
                elif rule == "lamb":
                    b1, b2, eps, bias_corr, lo, up = baked
                    # mirrors lamb_update_phase1/phase2 exactly: state
                    # m/v are always f32 (create_state), so the bias
                    # correction and trust-ratio math stay in f32 — lr
                    # multiplies the f32 update (the eager Python float
                    # weak-types to f32 there), hence no cdt cast on lr
                    nm, nv = cores.moments(tst[0], tst[1], gp, b1, b2)
                    nm = nm.astype(tst[0].dtype)
                    nv = nv.astype(tst[1].dtype)
                    if bias_corr:
                        coef1s, coef2s = extras
                        mhat = nm / coef1s[k]
                        vhat = nv / coef2s[k]
                    else:
                        mhat, vhat = nm, nv
                    upd = mhat / (jnp.sqrt(vhat) + eps) + wd * tw
                    r1 = jnp.linalg.norm(tw.ravel())
                    r2 = jnp.linalg.norm(upd.ravel())
                    if lo > 0:
                        r1 = jnp.maximum(r1, lo)
                    if up > 0:
                        r1 = jnp.minimum(r1, up)
                    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
                    nw = tw - lrs[k] * ratio * upd
                    nst = (nm, nv)
                elif rule == "rmsprop":
                    g1, eps = baked
                    nw, nst = cores.rmsprop(tw, gp, tst, lr, g1, eps)
                else:
                    eps, = baked
                    nw, nst = cores.adagrad(tw, gp, tst, lr, eps, wd)
                new_sts.append((nw, nst) if mp_pattern[k] else nst)
                new_ws.append(nw.astype(w.dtype))
            new_ws, new_sts = tuple(new_ws), tuple(new_sts)
            if not guard:
                h = _health.train_step_health(gs, ws, new_ws) \
                    if health_on else None
                return (new_ws, new_sts, None, None) \
                    + ((h,) if health_on else ())
            ok = jnp.asarray(True) if allfin is None else allfin
            # grads gate to ZERO on a skipped step (the eager guard
            # zeroes them so grad_req='add' does not re-poison the next
            # step); on a clean step they pass through into fresh
            # buffers (theirs were donated)
            out_ws = tuple(jnp.where(ok, a, b)
                           for a, b in zip(new_ws, ws))
            h = _health.train_step_health(gs, ws, out_ws) \
                if health_on else None
            return (out_ws,
                    jax.tree.map(lambda a, b: jnp.where(ok, a, b),
                                 new_sts, states),
                    tuple(jnp.where(ok, g, jnp.zeros_like(g)) for g in gs),
                    ok) + ((h,) if health_on else ())

        jitted = jax.jit(fn, donate_argnums=(0, 1, 2) if guard else (0, 2))
        return _telemetry.instrument_jit("fused_update", jitted)
