"""Fused whole-tree optimizer step: ONE donated jit dispatch per
``Trainer.step``.

The eager Gluon update loop pays one dispatch per parameter per step —
the dispatch-overhead wall PyGraph (arXiv:2503.19779) attacks with graph
capture, and the dominant step-time term on TPU once compute is sharded
(arXiv:2004.13336).  :class:`FusedUpdater` gathers every
``(weight, grad, state)`` triple into one pytree and applies the update
rule — the same pure cores the per-param ops and the SPMD path use
(``optimizer/cores.py``) — as a single ``jax.jit`` call with donated
buffers, so XLA fuses hundreds of tiny updates into one executable and
reuses the parameter memory in place.

What is folded inside the compiled program:

* grad rescale (traced scalar — changing batch size does NOT recompile),
* ``clip_gradient`` (traced scalar when enabled),
* per-param lr / wd multipliers (traced ``(n,)`` vectors — lr schedules
  and ``set_learning_rate`` do not recompile),
* multi-precision fp16 master weights (fp32 master in the state, fp16
  view written back, exactly like ``update_multi_precision``),
* the ``skip_nonfinite`` guard: the all-finite check
  (``amp.all_finite_flag`` — the SAME reduction the eager guard uses)
  becomes a fused reduction whose result gates every output through
  ``jnp.where``, so the guard costs no blocking host sync per step;
  skipped-step counting moves to an async readback
  (``Trainer.sync_nonfinite_guard`` forces it).

Compiled programs are cached by static configuration — (rule, baked
hyperparameters, multi-precision/wd patterns, clip/guard flags) — and by
tree structure/shapes/dtypes (jax's own jit cache).  Changing a baked
hyperparameter (momentum, betas, epsilon) recompiles; changing lr, wd,
rescale, or clip values does not.

Numerics: bit-compatible with the per-param loop it replaces — the cores
keep expression and evaluation order identical, traced scalars are cast
to each param's compute dtype (matching the weak-typed Python floats the
eager ops receive, so fp16/bf16 params without master weights stay in
their own dtype), and host-side bookkeeping (update counts, Adam
bias-corrected lr in Python doubles) mirrors the eager classes —
asserted by tests/test_fused_optimizer.py.  Documented divergences:
update counts advance even on a guard-skipped step (the flag is not
known at dispatch time; the eager guard skips the whole update including
the count), and low-precision params may differ from the loop by ~1 ulp
because the single fused program keeps elementwise intermediates in f32
where the op-by-op dispatch rounds at every op boundary.
"""
from __future__ import annotations

import math
import warnings
from typing import Dict, Optional, Tuple

from .. import telemetry as _telemetry
from ..ndarray.ndarray import NDArray
from .optimizer import SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, Updater

__all__ = ["FusedUpdater", "functional_twin"]

# exact-type table: NAG subclasses SGD but has a different rule; LARS /
# Signum / centered-RMSProp etc. are absent → per-param fallback
_RULES = {SGD: "sgd", NAG: "nag", Adam: "adam", AdamW: "adamw",
          RMSProp: "rmsprop", AdaGrad: "adagrad"}

# rules whose eager kernel folds wd into the gradient (prep_grad) only
# when wd != 0; adamw/adagrad apply wd decoupled, unconditionally
_FOLD_WD = ("sgd", "nag", "adam", "rmsprop")


def functional_twin(optimizer):
    """A ``parallel.optim`` FunctionalOptimizer matching an eager
    optimizer instance — the bridge CompiledLoop / SPMDTrainer use to
    take over a model configured for the eager ``Trainer``.

    Raises :class:`MXNetError` when the eager configuration carries
    host-side per-step behavior a pure traced update cannot reproduce
    (lr_scheduler callbacks, rescale_grad, clip_gradient, centered /
    clip_weights RMSProp) — callers should surface that and stay on the
    per-step path rather than silently change numerics.  Note adam's
    bias correction rounds differently between the tiers (host doubles
    folded into lr here vs. traced f32 in the functional core), a
    documented ~1-ulp-class divergence; sgd/nag are bit-exact.
    """
    from ..base import MXNetError
    from ..parallel import optim as _fopt   # lazy: avoids import cycle

    rule = _RULES.get(type(optimizer))
    if rule is None:
        raise MXNetError(
            f"no functional twin for {type(optimizer).__name__} — "
            "pass a parallel.optim optimizer explicitly")
    if getattr(optimizer, "lr_scheduler", None) is not None:
        raise MXNetError(
            "functional_twin cannot capture a host-side lr_scheduler — "
            "pass lr_schedule= (a traced step -> lr callable) to the "
            "functional optimizer instead")
    if float(optimizer.rescale_grad) != 1.0:
        raise MXNetError(
            "functional_twin: rescale_grad != 1 has no functional "
            "equivalent (the SPMD/loss path already means over the "
            "batch)")
    if optimizer.clip_gradient:
        raise MXNetError(
            "functional_twin: clip_gradient is not traced by the "
            "functional cores yet")
    kw = dict(learning_rate=optimizer.lr, wd=optimizer.wd)
    if rule in ("sgd", "nag"):
        kw["momentum"] = optimizer.momentum
    elif rule in ("adam", "adamw"):
        kw.update(beta1=optimizer.beta1, beta2=optimizer.beta2,
                  epsilon=optimizer.epsilon)
    elif rule == "rmsprop":
        if optimizer.centered or optimizer.clip_weights:
            raise MXNetError(
                "functional_twin: centered / clip_weights RMSProp is "
                "outside the functional envelope")
        kw.update(gamma1=optimizer.gamma1, epsilon=optimizer.epsilon)
    else:                                   # adagrad
        kw["epsilon"] = optimizer.float_stable_eps
    return _fopt.create(rule, **kw)


def _raw_state(s):
    """updater.states[i] structure (NDArrays / tuples / None) → raw jax
    arrays with the same structure."""
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s._data
    return tuple(_raw_state(x) for x in s)


def _writeback_state(s, new):
    """Write raw output arrays back into the (stable) NDArray wrappers —
    save/load_states and a later fallback to the loop keep working."""
    if s is None:
        return
    if isinstance(s, NDArray):
        s._set_data(new)
        return
    for a, b in zip(s, new):
        _writeback_state(a, b)


class FusedUpdater:
    """Whole-tree fused twin of :class:`optimizer.Updater`.

    Shares the wrapped Updater's ``states`` dict and ``optimizer``
    (re-read every step, so ``set_states`` / ``load_states`` swapping
    the optimizer keeps working), creates missing states exactly like
    the eager path, and leaves the per-param loop usable at any time —
    :meth:`step` returns ``(False, None)`` whenever the current
    optimizer or parameter set is outside the fused envelope.
    """

    def __init__(self, updater: Updater):
        self._updater = updater
        self._cache: Dict[tuple, object] = {}

    # -- per-step host side --------------------------------------------
    def step(self, updatable, guard: bool):
        """Apply one fused update to ``updatable`` (list of
        ``(index, Parameter)``).

        Returns ``(handled, flag)``: ``handled`` False means the caller
        must run the per-param loop instead; ``flag`` is the device-side
        all-finite bool (only when ``guard``) for async readback."""
        import numpy as np
        import jax

        opt = self._updater.optimizer
        rule = _RULES.get(type(opt))
        if rule is None:
            return False, None
        if rule == "rmsprop" and (opt.centered or opt.clip_weights):
            return False, None
        n = len(updatable)
        if n == 0:
            return True, None

        ws_nd, gs_nd = [], []
        for _, p in updatable:
            if p.stype != "default" or \
                    getattr(p, "_grad_stype", "default") != "default":
                return False, None
            ws_nd.append(p.data())
            gs_nd.append(p.grad())

        states = self._updater.states
        for (i, _), w in zip(updatable, ws_nd):
            if i not in states:
                states[i] = opt.create_state_multi_precision(i, w)

        ws = tuple(w._data for w in ws_nd)
        gs = tuple(g._data for g in gs_nd)
        sts = tuple(_raw_state(states[i]) for i, _ in updatable)
        donated = list(ws) + jax.tree_util.tree_leaves(sts) + \
            (list(gs) if guard else [])
        if len({id(x) for x in donated}) != len(donated):
            # aliased buffers cannot be donated — bail BEFORE touching
            # update counts / lr bookkeeping, so the per-param fallback
            # (which advances them itself) sees them exactly once
            return False, None

        # host bookkeeping in eager order: every param's count advances
        # before any lr is read, so a shared lr_scheduler sees the same
        # num_update for the whole tree (what the per-param loop
        # converges to after the first param)
        for i, _ in updatable:
            opt._update_count(i)
        mp_pattern, wd_pattern = [], []
        lrs = np.empty(n, np.float32)
        wds = np.empty(n, np.float32)
        for k, (i, p) in enumerate(updatable):
            lr, wd = opt._get_lr(i), opt._get_wd(i)
            if rule == "adam":
                # bias correction folds into lr in Python doubles, then
                # rounds once — the same bits the eager Adam class feeds
                # adam_update
                t = opt._index_update_count[i]
                lr *= math.sqrt(1. - opt.beta2 ** t) / (1. - opt.beta1 ** t)
            lrs[k] = lr
            wds[k] = wd
            wd_pattern.append(bool(wd))
            mp_pattern.append(bool(opt.multi_precision
                                   and ws_nd[k].dtype == np.float16))
        if rule == "adamw":
            counts = [opt._index_update_count[i] for i, _ in updatable]
            extras = (np.array([1. - opt.beta1 ** t for t in counts],
                               np.float32),
                      np.array([1. - opt.beta2 ** t for t in counts],
                               np.float32))
        else:
            extras = ()

        clip = opt.clip_gradient
        clip_on = bool(clip and clip > 0)
        if rule in ("sgd", "nag"):
            baked = (opt.momentum,)
        elif rule in ("adam", "adamw"):
            baked = (opt.beta1, opt.beta2, opt.epsilon)
        elif rule == "rmsprop":
            baked = (opt.gamma1, opt.epsilon)
        else:
            baked = (opt.float_stable_eps,)

        key = (rule, n, baked, tuple(mp_pattern), tuple(wd_pattern),
               clip_on, guard)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._cache[key] = self._build(key)
        # donation is best-effort: CPU jax has no buffer donation —
        # harmless, the dispatch win stands — and the per-call warning
        # is pure noise.  Scoped here so user jax code keeps seeing it.
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            new_ws, new_sts, new_gs, flag = fn(
                ws, gs, sts, lrs, wds, extras,
                np.float32(opt.rescale_grad),
                np.float32(clip if clip_on else 0.0))

        for k, (i, _) in enumerate(updatable):
            ws_nd[k]._set_data(new_ws[k])
            _writeback_state(states[i], new_sts[k])
            if new_gs is not None:
                gs_nd[k]._set_data(new_gs[k])
        c = _telemetry.counter(
            "mxtpu_optimizer_fused_updates",
            "whole-tree fused optimizer dispatches "
            "(one jit call updating every parameter)")
        c.inc(site="fused_update")
        _telemetry.gauge(
            "mxtpu_optimizer_dispatches_per_step",
            "optimizer-update dispatches in the last trainer step "
            "(1 = fused; num_params = per-param loop)").set(1)
        return True, flag

    # -- compiled side -------------------------------------------------
    def _build(self, key):
        import jax
        import jax.numpy as jnp
        from . import cores
        from ..contrib.amp.loss_scaler import all_finite_flag

        rule, n, baked, mp_pattern, wd_pattern, clip_on, guard = key

        def fn(ws, gs, states, lrs, wds, extras, rescale, clip):
            # guard decides on the RAW grads (pre-rescale), exactly like
            # the eager _grads_nonfinite → amp.all_finite check
            allfin = all_finite_flag(gs) if guard else None
            new_ws, new_sts = [], []
            for k in range(n):
                w, g, st = ws[k], gs[k], states[k]
                if mp_pattern[k]:
                    w32, inner = st
                    tw, tst, gk = w32, inner, g.astype(jnp.float32)
                else:
                    tw, tst, gk = w, st, g
                # the eager ops take Python-float hyperparameters, which
                # jax weak-types to the array dtype — fp16/bf16 params
                # without master weights compute (and stay) in their own
                # dtype.  The traced scalars here are strongly-typed
                # f32, so cast them to the compute dtype (a no-op for
                # f32 weights and fp32 master weights) to keep the
                # arithmetic and output dtypes identical to the loop.
                cdt = tw.dtype
                lr, wd = lrs[k].astype(cdt), wds[k].astype(cdt)
                gp = cores.prep_grad(
                    gk, rescale.astype(cdt),
                    clip.astype(cdt) if clip_on else None,
                    wd if (rule in _FOLD_WD and wd_pattern[k]) else None,
                    tw)
                if rule in ("sgd", "nag"):
                    momentum, = baked
                    if tst is None:
                        nw, nst = cores.sgd(tw, gp, lr), None
                    elif rule == "sgd":
                        nw, nst = cores.sgd_momentum(tw, gp, tst, lr,
                                                     momentum)
                    else:
                        nw, nst = cores.nag_momentum(tw, gp, tst, lr,
                                                     momentum)
                elif rule == "adam":
                    b1, b2, eps = baked
                    nw, nm, nv = cores.adam(tw, gp, tst[0], tst[1], lr,
                                            b1, b2, eps)
                    nst = (nm, nv)
                elif rule == "adamw":
                    b1, b2, eps = baked
                    coef1s, coef2s = extras
                    nw, nm, nv = cores.adamw(tw, gp, tst[0], tst[1], lr,
                                             wd, b1, b2, eps,
                                             coef1s[k].astype(cdt),
                                             coef2s[k].astype(cdt))
                    nst = (nm, nv)
                elif rule == "rmsprop":
                    g1, eps = baked
                    nw, nst = cores.rmsprop(tw, gp, tst, lr, g1, eps)
                else:
                    eps, = baked
                    nw, nst = cores.adagrad(tw, gp, tst, lr, eps, wd)
                new_sts.append((nw, nst) if mp_pattern[k] else nst)
                new_ws.append(nw.astype(w.dtype))
            new_ws, new_sts = tuple(new_ws), tuple(new_sts)
            if not guard:
                return new_ws, new_sts, None, None
            ok = jnp.asarray(True) if allfin is None else allfin
            # grads gate to ZERO on a skipped step (the eager guard
            # zeroes them so grad_req='add' does not re-poison the next
            # step); on a clean step they pass through into fresh
            # buffers (theirs were donated)
            return (tuple(jnp.where(ok, a, b) for a, b in zip(new_ws, ws)),
                    jax.tree.map(lambda a, b: jnp.where(ok, a, b),
                                 new_sts, states),
                    tuple(jnp.where(ok, g, jnp.zeros_like(g)) for g in gs),
                    ok)

        jitted = jax.jit(fn, donate_argnums=(0, 1, 2) if guard else (0, 2))
        return _telemetry.instrument_jit("fused_update", jitted)
