"""Executor: the bound, compiled form of a Symbol (reference:
src/executor/graph_executor.cc GraphExecutor + python/mxnet/executor.py).

TPU-native re-design: ``bind`` does not run memory-planning / op-exec
attachment passes — it closes the symbol graph over a pure function and
``jax.jit``s it.  XLA buffer assignment subsumes PlanMemory, XLA fusion
subsumes op bulking, and autodiff is ``jax.vjp`` of the same function
(subsuming the nnvm Gradient pass).  Forward and backward are each one
compiled program; backward recomputes forward inside the compiled region
(rematerialization — the XLA-idiomatic trade, cheaper than keeping every
intermediate live in HBM).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as _np

from .base import MXNetError
from .context import Context, current_context
from . import ndarray as nd
from . import telemetry as _telemetry
from .ndarray.ndarray import NDArray


class Executor:
    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, _shapes_hint=None):
        from . import autograd  # noqa: F401  (scope helpers used in _run)
        self._symbol = symbol
        self._ctx = ctx if ctx is not None else current_context()
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self._out_names = symbol.list_outputs()

        self.arg_arrays = self._canon_arrays(args, self._arg_names, "args")
        self.aux_arrays = self._canon_arrays(aux_states, self._aux_names,
                                             "aux_states", allow_empty=True)

        # grad_req: str | list | dict
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null")
                              for n in self._arg_names}

        if args_grad is None:
            args_grad = {}
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(self._arg_names, args_grad))
        self.grad_arrays = []
        for n, a in zip(self._arg_names, self.arg_arrays):
            if self._grad_req.get(n, "null") == "null":
                self.grad_arrays.append(None)
            elif n in args_grad:
                self.grad_arrays.append(args_grad[n])
            else:
                self.grad_arrays.append(nd.zeros(a.shape, ctx=self._ctx,
                                                 dtype=a.dtype))

        self.outputs: List[NDArray] = []
        self._fwd_cache: Dict[bool, object] = {}
        self._bwd_cache = None
        self._last_primals = None

    # ------------------------------------------------------------------
    @classmethod
    def simple_bind(cls, symbol, ctx=None, grad_req="write", type_dict=None,
                    **shapes):
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shapes)
        type_dict = type_dict or {}
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        args = {n: nd.zeros(s, ctx=ctx,
                            dtype=type_dict.get(n, _np.float32))
                for n, s in zip(arg_names, arg_shapes)}
        # moving_var-style aux start at the reference's init values when the
        # user never writes them: mean 0, var 1
        aux = {}
        for n, s in zip(aux_names, aux_shapes):
            init = nd.ones if n.endswith("_var") else nd.zeros
            aux[n] = init(s, ctx=ctx, dtype=type_dict.get(n, _np.float32))
        return cls(symbol, ctx, args=args, grad_req=grad_req,
                   aux_states=aux)

    def _canon_arrays(self, vals, names, what, allow_empty=False):
        if vals is None:
            if allow_empty:
                vals = {}
            else:
                raise MXNetError(f"bind: {what} is required")
        if isinstance(vals, dict):
            missing = [n for n in names if n not in vals]
            if missing and not allow_empty:
                raise MXNetError(f"bind: {what} missing entries for "
                                 f"{missing}")
            out = []
            for n in names:
                v = vals.get(n)
                if v is None:
                    raise MXNetError(f"bind: {what} missing '{n}'")
                out.append(self._as_nd(v))
            return out
        vals = list(vals)
        if len(vals) != len(names):
            raise MXNetError(
                f"bind: {what} has {len(vals)} entries, expected "
                f"{len(names)} ({names})")
        return [self._as_nd(v) for v in vals]

    def _as_nd(self, v) -> NDArray:
        if isinstance(v, NDArray):
            return v
        return nd.array(v, ctx=self._ctx)

    # ------------------------------------------------------------------
    @property
    def arg_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self) -> Dict[str, Optional[NDArray]]:
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self) -> Dict[str, NDArray]:
        return dict(zip(self._out_names, self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for n, v in (arg_params or {}).items():
            if n in self._arg_names:
                self.arg_arrays[self._arg_names.index(n)] = self._as_nd(v)
            elif not allow_extra_params:
                raise MXNetError(f"copy_params_from: unknown arg '{n}'")
        for n, v in (aux_params or {}).items():
            if n in self._aux_names:
                self.aux_arrays[self._aux_names.index(n)] = self._as_nd(v)
            elif not allow_extra_params:
                raise MXNetError(f"copy_params_from: unknown aux '{n}'")

    # ------------------------------------------------------------------
    # compiled graph functions
    # ------------------------------------------------------------------
    def _pure_fn(self, is_train: bool):
        """(arg_vals, aux_vals, key) -> (outputs, new_aux) as jax arrays."""
        from .symbol.symbol import eval_graph
        from . import autograd as ag
        from . import random as _random
        symbol = self._symbol
        arg_names, aux_names = self._arg_names, self._aux_names

        def run(arg_vals, aux_vals, key):
            values = {n: NDArray(a) for n, a in zip(arg_names, arg_vals)}
            values.update(
                {n: NDArray(a) for n, a in zip(aux_names, aux_vals)})
            aux_sink: Dict[str, object] = {}
            with ag.pause(train_mode=is_train), _random.trace_stream(key):
                outs = eval_graph(symbol, values, is_train, aux_sink)
            new_aux = []
            for n, a in zip(aux_names, aux_vals):
                upd = aux_sink.get(n)
                new_aux.append(upd._data if isinstance(upd, NDArray)
                               else (upd if upd is not None else a))
            return tuple(o._data for o in outs), tuple(new_aux)
        return run

    def _fwd(self, is_train: bool):
        if is_train not in self._fwd_cache:
            import jax
            self._fwd_cache[is_train] = _telemetry.instrument_jit(
                "executor", jax.jit(self._pure_fn(is_train)))
        return self._fwd_cache[is_train]

    def _bwd(self):
        if self._bwd_cache is None:
            import jax
            run = self._pure_fn(True)
            diff_idx = [i for i, n in enumerate(self._arg_names)
                        if self._grad_req.get(n, "null") != "null"]

            def bwd(arg_vals, aux_vals, key, cotangents):
                def f(*diff_vals):
                    full = list(arg_vals)
                    for k, v in zip(diff_idx, diff_vals):
                        full[k] = v
                    outs, _ = run(tuple(full), aux_vals, key)
                    return outs
                diff_vals = [arg_vals[k] for k in diff_idx]
                _, vjp_fn = jax.vjp(f, *diff_vals)
                return vjp_fn(tuple(cotangents))
            self._bwd_cache = (_telemetry.instrument_jit(
                "executor", jax.jit(bwd)), diff_idx)
        return self._bwd_cache

    # ------------------------------------------------------------------
    def forward(self, is_train: bool = False, **kwargs) -> List[NDArray]:
        with _telemetry.trace_span("executor.forward", cat="executor",
                                   is_train=bool(is_train)):
            for n, v in kwargs.items():
                if n not in self._arg_names:
                    raise MXNetError(f"forward: unknown input '{n}'")
                self.arg_arrays[self._arg_names.index(n)] = self._as_nd(v)
            from . import random as _random
            key = _random.new_key(self._ctx)
            arg_vals = tuple(a._data for a in self.arg_arrays)
            aux_vals = tuple(a._data for a in self.aux_arrays)
            outs, new_aux = self._fwd(bool(is_train))(arg_vals, aux_vals,
                                                      key)
            self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
            if is_train:
                self._last_primals = (arg_vals, aux_vals, key)
                for a, v in zip(self.aux_arrays, new_aux):
                    a._data = v
            return self.outputs

    def backward(self, out_grads=None) -> None:
        if self._last_primals is None:
            raise MXNetError("backward called before forward(is_train=True)")
        with _telemetry.trace_span("executor.backward", cat="executor"):
            arg_vals, aux_vals, key = self._last_primals
            if out_grads is None:
                import jax.numpy as jnp
                cots = [jnp.ones(o.shape, o.dtype) for o in self.outputs]
            else:
                if isinstance(out_grads, NDArray):
                    out_grads = [out_grads]
                cots = [self._as_nd(g)._data for g in out_grads]
            bwd, diff_idx = self._bwd()
            grads = bwd(arg_vals, aux_vals, key, tuple(cots))
            for k, g in zip(diff_idx, grads):
                name = self._arg_names[k]
                if self._grad_req[name] == "add":
                    self.grad_arrays[k]._data = \
                        self.grad_arrays[k]._data + g
                else:
                    self.grad_arrays[k]._data = g

    # ------------------------------------------------------------------
    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **new_shapes):
        """Rebind with new input shapes (reference: Executor::Reshape).
        Compilation is per-shape under XLA; the jit cache keys on shapes, so
        this just re-allocates the changed inputs."""
        args = {}
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**new_shapes)
        for n, s, old in zip(self._arg_names, arg_shapes, self.arg_arrays):
            if tuple(s) != tuple(old.shape):
                args[n] = nd.zeros(s, ctx=self._ctx, dtype=old.dtype)
            else:
                args[n] = old
        aux = {}
        for n, s, old in zip(self._aux_names, aux_shapes, self.aux_arrays):
            aux[n] = old if tuple(s) == tuple(old.shape) else \
                nd.zeros(s, ctx=self._ctx, dtype=old.dtype)
        return Executor(self._symbol, self._ctx, args=args,
                        grad_req=self._grad_req, aux_states=aux)

    def __repr__(self):
        return (f"<Executor {self._symbol.name}: "
                f"{len(self._arg_names)} args, {len(self._aux_names)} aux>")
