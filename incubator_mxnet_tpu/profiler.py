"""``mx.profiler`` — profiling API (reference: python/mxnet/profiler.py;
native side src/profiler/profiler.{h,cc}, aggregate_stats.cc).

Two complementary planes, mirroring the reference's design:

* **Op-level table + chrome://tracing JSON** — while running, every eager op
  dispatch is bracketed (the analog of ``ProfileOperator`` wrapping
  ``ThreadedEngine::ExecuteOprBlock``); ops run synchronously during
  profiling so durations are true compute times.  ``dump()`` writes
  chrome-trace JSON (the reference's output format) including ``ph:"C"``
  counter tracks (profiler Counters + telemetry counters when the
  telemetry collector is on) and ``ph:"i"`` instant events (Markers);
  ``dumps()`` returns the min/max/avg aggregate table (reference:
  aggregate_stats.cc).
* **XLA trace** — ``set_config(xla_trace_dir=...)`` additionally records a
  jax.profiler trace (TensorBoard/Perfetto), the TPU-native superset of
  the reference's NVTX/VTune emitters.

Op events arrive via the telemetry event bus (``telemetry.OP_TIMED``), so
the profiler and the telemetry collector can observe the same op stream
concurrently — there is no single observer slot to fight over.

Env autostart: ``MXNET_PROFILER_AUTOSTART=1`` (reference parity).
"""
from __future__ import annotations

import json
import threading
import time

from .base import MXNetError, getenv_bool
from . import telemetry as _telemetry

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "Task", "Frame", "Counter", "Marker", "scope"]

_lock = threading.Lock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": True,
    "xla_trace_dir": None,
}
_state = "stop"
_paused = False
# (ph, name, t_start_us, value) — ph "X": value = dur_us;
# ph "C": value = counter value; ph "i": value unused
_events = []
_t0 = None
_xla_tracing = False
_run_start_counters = {}   # telemetry counter sample taken at set_state(run)


def set_config(**kwargs):
    """reference: mx.profiler.set_config."""
    unknown = set(kwargs) - set(_config)
    if unknown:
        raise MXNetError(f"profiler.set_config: unknown options {unknown}")
    _config.update(kwargs)


def _observer(name, seconds):
    if _paused or _t0 is None:
        return
    now = time.perf_counter()
    with _lock:
        _events.append(("X", name, (now - seconds - _t0) * 1e6,
                        seconds * 1e6))


def _emit(ph, name, value=None):
    """Record a counter sample / instant event at 'now' while running."""
    if _state != "run" or _paused or _t0 is None:
        return
    ts = (time.perf_counter() - _t0) * 1e6
    with _lock:
        _events.append((ph, name, ts, value))


def set_state(state="stop"):
    """'run' starts op bracketing (+XLA trace if configured); 'stop' ends
    it (reference: mx.profiler.set_state).  Each new run starts a FRESH
    session: prior events are cleared, the clock re-zeroed, and a stale
    pause() undone — back-to-back sessions never mix timelines."""
    global _state, _t0, _paused, _xla_tracing, _run_start_counters
    if state == "run":
        with _lock:
            _events.clear()
        _paused = False
        _t0 = time.perf_counter()
        if _state != "run":          # transition only: tracer is refcounted
            _telemetry.tracer.enable()
        _state = "run"
        _telemetry.OP_TIMED.subscribe(_observer)
        _run_start_counters = (_telemetry.counters_flat()
                               if _telemetry.enabled() else {})
        if _config["xla_trace_dir"] and not _xla_tracing:
            import jax
            jax.profiler.start_trace(_config["xla_trace_dir"])
            _xla_tracing = True
    elif state == "stop":
        if _state == "run":
            _telemetry.tracer.disable()
        _state = "stop"
        _telemetry.OP_TIMED.unsubscribe(_observer)
        if _xla_tracing:
            import jax
            jax.profiler.stop_trace()
            _xla_tracing = False
    else:
        raise MXNetError("profiler state must be 'run' or 'stop'")


def state():
    return _state


def pause(profile_process="worker"):
    global _paused
    _paused = True


def resume(profile_process="worker"):
    global _paused
    _paused = False


def _trace_event(ph, name, ts, value):
    if ph == "X":
        return {"name": name, "ph": "X", "ts": ts, "dur": value,
                "pid": 0, "tid": 0, "cat": "operator"}
    if ph == "C":
        return {"name": name, "ph": "C", "ts": ts, "pid": 0,
                "cat": "counter", "args": {"value": value}}
    return {"name": name, "ph": "i", "ts": ts, "pid": 0, "tid": 0,
            "cat": "marker", "s": "p"}


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON to the configured filename
    (reference: MXDumpProfile → chrome trace).  Telemetry counters (when
    the collector is on) are woven in as ``ph:"C"`` samples: one at the
    run start, one at dump time — a per-session delta track on top of the
    profiler's own Counter series."""
    with _lock:
        events = list(_events)
    trace_events = [_trace_event(*e) for e in events]
    if _t0 is not None:
        # telemetry spans from this session nest as ph:"X" flame-graph
        # rows next to the op events (main thread shares tid 0)
        trace_events.extend(_telemetry.tracer.chrome_events(_t0))
    if _telemetry.enabled() and _t0 is not None:
        now_ts = (time.perf_counter() - _t0) * 1e6
        current = _telemetry.counters_flat()
        for name, v in sorted(current.items()):
            if name in _run_start_counters:
                trace_events.append(
                    _trace_event("C", name, 0.0, _run_start_counters[name]))
            trace_events.append(_trace_event("C", name, now_ts, v))
    trace = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    with open(_config["filename"], "w") as f:
        json.dump(trace, f)


def dumps(reset=False):
    """Aggregate per-op stats table (reference: aggregate_stats.cc
    DumpTable): name, calls, total/min/max/avg ms.  Duration ("X") events
    only — counter/marker events live in the chrome trace."""
    with _lock:
        events = list(_events)
        if reset:
            _events.clear()
    agg = {}
    for ph, name, _ts, dur in events:
        if ph != "X":
            continue
        rec = agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        rec[0] += 1
        rec[1] += dur
        rec[2] = min(rec[2], dur)
        rec[3] = max(rec[3], dur)
    lines = [f"{'Name':<28}{'Calls':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}{'Avg(ms)':>10}"]
    for name, (calls, total, mn, mx) in sorted(
            agg.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"{name:<28}{calls:>8}{total/1e3:>12.3f}"
                     f"{mn/1e3:>10.3f}{mx/1e3:>10.3f}"
                     f"{total/calls/1e3:>10.3f}")
    return "\n".join(lines)


class _Named:
    def __init__(self, name):
        self.name = name
        self._start = None

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is None:
            return
        dur = time.perf_counter() - self._start
        if _state == "run":
            _observer(f"{type(self).__name__}:{self.name}", dur)
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Named):
    """reference: MXProfileCreateTask."""


class Frame(_Named):
    """reference: MXProfileCreateFrame."""


class Marker:
    """Instant event (reference: MXProfileSetMarker) — appears in dump()
    as a chrome-trace ``ph:"i"`` event."""

    def __init__(self, name):
        self.name = name

    def mark(self, scope="process"):
        _emit("i", f"Marker:{self.name}")


class Counter:
    """reference: MXProfileCreateCounter.  Every value change while the
    profiler runs is recorded as a chrome-trace ``ph:"C"`` sample, so the
    counter renders as a proper time series in the trace viewer."""

    def __init__(self, name, value=0):
        self.name = name
        self.value = value
        _emit("C", f"Counter:{self.name}", value)

    def set_value(self, value):
        self.value = value
        _emit("C", f"Counter:{self.name}", self.value)

    def increment(self, delta=1):
        self.value += delta
        _emit("C", f"Counter:{self.name}", self.value)

    def decrement(self, delta=1):
        self.value -= delta
        _emit("C", f"Counter:{self.name}", self.value)


class scope:
    """Name scope for profiling (reference: profiler_scope attr →
    jax.named_scope, so compiled-graph ops carry the name in XLA traces)."""

    def __init__(self, name):
        self.name = name
        self._cm = None

    def __enter__(self):
        import jax
        self._cm = jax.named_scope(self.name)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        self._cm.__exit__(*exc)


if getenv_bool("MXNET_PROFILER_AUTOSTART", False):
    set_state("run")
