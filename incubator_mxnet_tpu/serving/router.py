"""mxtpu-router — a fault-tolerant HTTP front tier over a fleet of
``mxtpu-serve`` replicas (docs/serving.md "Serving a fleet").

One replica is a fault domain: a process that can be SIGKILLed by the
scheduler, drained for a weight update, or wedged behind an exhausted
KV pool.  The router's job is to make all of that invisible to
clients.  Stdlib-only (``http.client`` upstream, the shared
:class:`~..http_util.BaseJSONHandler` downstream), so a fleet needs no
sidecar infrastructure — same deployment story as ``mxtpu-serve``
itself.  Five cooperating mechanisms:

* **health-aware balancing** — a background loop polls every replica's
  ``GET /readyz`` (readiness, blockers: warming models, ``slo:<m>``
  burn, ``kv:<m>`` starvation) and ``GET /slo`` (worst-model burn
  rate).  ``:predict`` traffic goes weighted least-loaded:
  ``score = (inflight + 1) * (1 + burn)``, so a replica burning error
  budget sheds load before it trips its own SLO blocker.
* **outlier ejection** — each replica carries a
  :class:`~.lifecycle.CircuitBreaker` fed by transport-level failures
  (connect refused/reset, request timeouts, mid-stream death) from
  both the health loop and the request path.  ``threshold``
  consecutive failures eject the replica (OPEN → out of rotation);
  the health loop keeps probing and its first success re-admits it.
  An HTTP 503 from a *responding* replica is not a transport failure —
  it flips ``ready`` off without charging the breaker.
* **retry with failover** — connect errors, 429 and 503 re-route to
  another replica under a per-request retry budget
  (``MXNET_ROUTER_RETRIES``), through :func:`fault.retry_call` with
  the ``retry_after_hint`` extractor: a server-sent ``Retry-After``
  parks that replica (``backoff_until``) and, when no alternative
  replica exists, becomes the actual sleep before the next attempt.
  The request body is read once and the identical bytes are replayed,
  and the client's ``X-Request-Id`` rides every hop, so one id
  correlates client ↔ router ↔ whichever replica finally answered.
* **SSE passthrough** — ``:generate`` streams are relayed chunk-for-
  chunk (:meth:`~..http_util.BaseJSONHandler.relay_chunk`).  A replica
  that dies before emitting its first SSE event is a retryable
  failure: the router fails over and the client never knows.  Once
  tokens are on the wire the stream cannot be transparently replayed,
  so a mid-stream death terminates with an SSE ``error`` event
  carrying the request id — never a silent hang.  A *client*
  disconnect closes the upstream connection, which the replica sees as
  its own client vanishing → ``Cancelled`` → KV blocks and the decode
  slot free at the next step boundary.
* **drain orchestration** — ``POST /admin/drain {"replica": id}``
  stops routing to the replica *first*, then forwards the drain (its
  ``/readyz`` flips for any other balancer), then waits for the
  router's in-flight count on it to hit zero: the zero-downtime half
  of a rolling weight update.  ``/admin/undrain`` reverses it and
  re-polls health so the replica rejoins immediately.

Generation traffic is **prefix-affine**: requests whose token prefix
shares the same leading ``MXNET_KV_BLOCK_SIZE``-aligned blocks (up to
``MXNET_ROUTER_AFFINITY_BLOCKS``) rendezvous-hash (highest-random-
weight over the *eligible* set, so membership churn moves only ~1/N of
the keyspace) to the same replica, concentrating the paged KV prefix
cache (``mxtpu_prefix_cache_hits``) instead of smearing identical
system prompts across the fleet.  When the owner is overloaded
(inflight exceeds the fleet minimum by ``MXNET_ROUTER_SPILL_MARGIN``)
the request spills down the rendezvous order — affinity is a
preference, never a hotspot.

Fault site: ``router.upstream`` fires once per upstream attempt
(kinds ``ioerror``/``latency``/``hang``), so CI can drill "the second
attempt's replica is dead" deterministically.  Metrics:
``mxtpu_router_*`` on the shared registry, exposed by the router's own
``/metrics``.  CLI: ``mxtpu-router --replica host:port ...``.
"""
from __future__ import annotations

import hashlib
import http.client
import json
import os
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import quote, urlsplit

from ..base import MXNetError, getenv, getenv_bool, getenv_int
from .. import fault as _fault
from .. import telemetry as _telemetry
from .. import telemetry_ring as _ring
from ..http_util import BaseJSONHandler, HTTPServerBase
from . import lifecycle as _lc
from . import metrics as _m
from . import slo as _slo

__all__ = ["Router", "Replica", "UpstreamError", "NoReplicaAvailable",
           "rendezvous_order", "prefix_key", "default_incident_dir"]

FAULT_SITE = "router.upstream"

# metric families owned by the control process (router + an in-process
# supervisor/autoscaler): rendered once from the local registry, stripped
# from replica snapshots so shared-registry test fleets don't double-count
CONTROL_PLANE_PREFIXES = ("mxtpu_router_", "mxtpu_supervise_",
                          "mxtpu_autoscale_")


def default_incident_dir() -> str:
    """Where correlated incident bundles land:
    ``MXNET_ROUTER_INCIDENT_DIR`` or ``<tmpdir>/mxtpu_incidents``."""
    return getenv("MXNET_ROUTER_INCIDENT_DIR") or \
        os.path.join(tempfile.gettempdir(), "mxtpu_incidents")

#: numeric encoding for the ``mxtpu_router_replica_state`` gauge
READY_CODE, UNREADY_CODE, DRAINING_CODE, EJECTED_CODE, DOWN_CODE = \
    0, 1, 2, 3, 4

_HOP_HEADERS = ("content-type", "retry-after")  # upstream headers kept
_TERMINAL_MARKS = (b"event: done", b"event: error")


class UpstreamError(MXNetError):
    """A retryable upstream failure: connect error, 429/503, or a
    stream that died before its first SSE event.  Carries the server's
    ``Retry-After`` (when one was sent and no alternative replica
    exists — otherwise 0 so failover is immediate);
    :func:`fault.retry_after_hint` reads it."""

    def __init__(self, msg: str, retry_after: Optional[float] = None,
                 replica: Optional[str] = None):
        super().__init__(msg)
        if retry_after is not None:
            self.retry_after = max(0.0, float(retry_after))
        self.replica = replica


class NoReplicaAvailable(UpstreamError):
    """No replica is eligible for new work right now (all ejected,
    draining, unready, or backing off)."""


def rendezvous_order(key: bytes, replicas: Sequence) -> List:
    """Highest-random-weight order of ``replicas`` for ``key``.  Each
    replica's weight is ``blake2b(key || 0 || replica_id)``, so every
    (key, replica) pair hashes independently: adding or removing one
    replica reassigns only the keys it wins/owned (~1/N of the
    keyspace), every other key keeps its owner.  ``replicas`` may be
    :class:`Replica` objects or plain id strings (tests)."""

    def weight(rep) -> bytes:
        rid = rep.id if hasattr(rep, "id") else str(rep)
        h = hashlib.blake2b(digest_size=8)
        h.update(key)
        h.update(b"\x00")
        h.update(rid.encode("utf-8"))
        return h.digest()

    return sorted(replicas, key=weight, reverse=True)


def prefix_key(tokens, block_size: int,
               max_blocks: int) -> Optional[bytes]:
    """The affinity key for a generation request: a digest of the
    leading ``block_size``-aligned token prefix, capped at
    ``max_blocks`` blocks.  Aligning to the KV block size means two
    requests share a key exactly when the paged prefix cache could
    share their leading blocks; capping keeps long unique tails from
    defeating affinity on a common system prompt.  None when the
    prompt is shorter than one block (no shareable block → no
    affinity)."""
    if not tokens or block_size <= 0:
        return None
    n = (len(tokens) // block_size) * block_size
    if n <= 0:
        return None
    if max_blocks > 0:
        n = min(n, max_blocks * block_size)
    h = hashlib.blake2b(digest_size=16)
    for t in tokens[:n]:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.digest()


def _parse_hostport(spec: str) -> Tuple[str, int]:
    spec = spec.strip()
    if "//" not in spec:
        spec = "//" + spec
    split = urlsplit(spec)
    host = split.hostname
    if not host or split.port is None:
        raise MXNetError(
            f"replica {spec!r}: expected host:port or http://host:port")
    return host, int(split.port)


class _HopLog:
    """Bounded per-request record of upstream attempts (hops).

    Every hop gets a span id from the tracer's process-wide sequence —
    the id stamped on the upstream ``X-Trace-Id`` header — so the
    replica's remote ``serve.request`` spans can name exactly which
    router attempt they served.  Works with the tracer off: the hop log
    IS the router's half of the stitched timeline, and routers don't
    require ``telemetry.start()`` to answer ``GET /trace``.  Evicts
    oldest requests beyond ``max_requests`` (LRU on request id)."""

    def __init__(self, max_requests: int = 512):
        self._lock = threading.Lock()
        self._by_rid: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._max = max(1, int(max_requests))

    def begin(self, rid: str, replica_id: str) -> dict:
        hop = {"sid": f"{next(_telemetry._span_seq):08x}",
               "replica": replica_id,
               "start_unix": round(time.time(), 6),
               "t0": time.monotonic(),
               "outcome": None}
        with self._lock:
            hops = self._by_rid.get(rid)
            if hops is None:
                hops = self._by_rid[rid] = []
                while len(self._by_rid) > self._max:
                    self._by_rid.popitem(last=False)
            else:
                self._by_rid.move_to_end(rid)
            hops.append(hop)
        return hop

    @staticmethod
    def end(hop: dict, outcome: str, error=None, status=None) -> None:
        hop["duration_s"] = round(time.monotonic() - hop["t0"], 6)
        hop["outcome"] = outcome
        if error is not None:
            hop["error"] = str(error)[:200]
        if status is not None:
            hop["status"] = int(status)

    @staticmethod
    def _view(hop: dict) -> dict:
        return {k: v for k, v in hop.items() if k != "t0"}

    def get(self, rid: str) -> List[dict]:
        with self._lock:
            return [self._view(h) for h in self._by_rid.get(rid, ())]

    def recent(self, limit: int = 32) -> List[dict]:
        with self._lock:
            items = list(self._by_rid.items())[-max(0, int(limit)):]
        return [{"request_id": rid,
                 "hops": [self._view(h) for h in hops]}
                for rid, hops in items]

    def request_ids_on(self, replica_id: str, failed: bool,
                       limit: int = 8) -> List[str]:
        """Newest-first request ids with a hop on ``replica_id`` —
        failed/open hops when ``failed`` (incident correlation), any
        otherwise."""
        out: List[str] = []
        with self._lock:
            for rid, hops in reversed(self._by_rid.items()):
                for h in hops:
                    if h["replica"] != replica_id:
                        continue
                    if failed and h["outcome"] == "ok":
                        continue
                    out.append(rid)
                    break
                if len(out) >= limit:
                    break
        return out


class Replica:
    """The router's view of one ``mxtpu-serve`` process."""

    def __init__(self, url: str,
                 eject_threshold: Optional[int] = None,
                 eject_cooldown_seconds: Optional[float] = None):
        self.host, self.port = _parse_hostport(url)
        self.id = f"{self.host}:{self.port}"
        self.breaker = _lc.CircuitBreaker(
            f"replica:{self.id}", threshold=eject_threshold,
            cooldown_seconds=eject_cooldown_seconds)
        self._lock = threading.Lock()
        self.ready = False          # last /readyz verdict
        self.reachable = False      # last poll/request connected at all
        self.draining = False       # router-side drain flag
        self.burn = 0.0             # worst-model SLO burn rate
        self.blockers: List[str] = []
        self.backoff_until = 0.0    # honored Retry-After
        self.last_error = ""
        self._inflight = 0

    # -- load accounting ------------------------------------------------
    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def _inflight_add(self, delta: int) -> None:
        with self._lock:
            self._inflight += delta
            _m.ROUTER_INFLIGHT.set(self._inflight, replica=self.id)

    # -- eligibility ----------------------------------------------------
    def eligible(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return (self.ready and not self.draining
                and self.breaker.state != _lc.OPEN
                and now >= self.backoff_until)

    def note_backoff(self, seconds: float) -> None:
        """Honor a server-sent ``Retry-After``: no new work for
        ``seconds`` (routing only — the health loop keeps polling)."""
        until = time.monotonic() + max(0.0, float(seconds))
        with self._lock:
            self.backoff_until = max(self.backoff_until, until)

    def state_code(self) -> int:
        if self.draining:
            return DRAINING_CODE
        if self.breaker.state == _lc.OPEN:
            return EJECTED_CODE
        if not self.reachable:
            return DOWN_CODE
        if not self.ready:
            return UNREADY_CODE
        return READY_CODE

    def snapshot(self) -> dict:
        code = self.state_code()
        name = {READY_CODE: "READY", UNREADY_CODE: "UNREADY",
                DRAINING_CODE: "DRAINING", EJECTED_CODE: "EJECTED",
                DOWN_CODE: "DOWN"}[code]
        return {"id": self.id, "state": name,
                "ready": self.ready, "reachable": self.reachable,
                "draining": self.draining,
                "breaker": self.breaker.state,
                "burn_rate": self.burn, "blockers": list(self.blockers),
                "inflight": self.inflight,
                "backoff_seconds": max(0.0, self.backoff_until
                                       - time.monotonic()),
                "last_error": self.last_error}

    def __repr__(self):
        return f"<Replica {self.id} {self.snapshot()['state']}>"


class _RouterHTTPServer(HTTPServerBase):
    router: "Router"


class Router:
    """Front tier over N replicas.  Programmatic use::

        router = Router(["127.0.0.1:8080", "127.0.0.1:8081"], port=0)
        router.start()
        ... client traffic against router.port ...
        router.stop()

    Constructor args override the ``MXNET_ROUTER_*`` env defaults
    (docs/env_var.md)."""

    def __init__(self, replicas: Sequence[str],
                 port: Optional[int] = None, host: str = "0.0.0.0",
                 retries: Optional[int] = None,
                 health_interval: Optional[float] = None,
                 affinity: Optional[bool] = None,
                 affinity_blocks: Optional[int] = None,
                 spill_margin: Optional[int] = None,
                 upstream_timeout: Optional[float] = None,
                 stream_timeout: Optional[float] = None,
                 retry_deadline: Optional[float] = None,
                 eject_threshold: Optional[int] = None,
                 eject_cooldown_seconds: Optional[float] = None,
                 federate_seconds: Optional[float] = None,
                 incident_dir: Optional[str] = None):
        if not replicas:
            raise MXNetError("Router needs at least one replica")
        self._port = getenv_int("MXNET_ROUTER_PORT", 8081) \
            if port is None else int(port)
        self._host = host
        self.retries = getenv_int("MXNET_ROUTER_RETRIES", 2) \
            if retries is None else int(retries)
        self.health_interval = float(
            getenv("MXNET_ROUTER_HEALTH_INTERVAL_SECONDS", 0.5)) \
            if health_interval is None else float(health_interval)
        self.affinity = getenv_bool("MXNET_ROUTER_AFFINITY", True) \
            if affinity is None else bool(affinity)
        self.affinity_blocks = getenv_int(
            "MXNET_ROUTER_AFFINITY_BLOCKS", 2) \
            if affinity_blocks is None else int(affinity_blocks)
        self.block_size = max(1, getenv_int("MXNET_KV_BLOCK_SIZE", 16))
        self.spill_margin = getenv_int("MXNET_ROUTER_SPILL_MARGIN", 8) \
            if spill_margin is None else int(spill_margin)
        self.upstream_timeout = float(
            getenv("MXNET_ROUTER_UPSTREAM_TIMEOUT_SECONDS", 10.0)) \
            if upstream_timeout is None else float(upstream_timeout)
        self.stream_timeout = float(
            getenv("MXNET_ROUTER_STREAM_TIMEOUT_SECONDS", 120.0)) \
            if stream_timeout is None else float(stream_timeout)
        self.retry_deadline = float(
            getenv("MXNET_ROUTER_RETRY_DEADLINE_SECONDS", 10.0)) \
            if retry_deadline is None else float(retry_deadline)
        if eject_threshold is None:
            eject_threshold = getenv_int("MXNET_ROUTER_EJECT_THRESHOLD", 3)
        if eject_cooldown_seconds is None:
            eject_cooldown_seconds = float(
                getenv("MXNET_ROUTER_EJECT_COOLDOWN_SECONDS", 2.0))
        # kept as attributes: replicas added after construction
        # (add_replica / POST /admin/replicas) get the same breaker knobs
        self.eject_threshold = int(eject_threshold)
        self.eject_cooldown_seconds = float(eject_cooldown_seconds)
        self._replicas: List[Replica] = []
        for spec in replicas:
            rep = Replica(spec, eject_threshold=self.eject_threshold,
                          eject_cooldown_seconds=self.eject_cooldown_seconds)
            if all(r.id != rep.id for r in self._replicas):
                self._replicas.append(rep)
        self._lock = threading.Lock()
        self._http: Optional[_RouterHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False
        self._rr = 0                # rotation offset for idle ties
        # -- fleet observability (docs/observability.md) ---------------
        self.federate_seconds = float(
            getenv("MXNET_ROUTER_FEDERATE_SECONDS", 2.0)) \
            if federate_seconds is None else float(federate_seconds)
        self.incident_dir = default_incident_dir() \
            if incident_dir is None else str(incident_dir)
        self.incident_debounce = 10.0   # seconds per (reason, replica)
        self.max_incidents = getenv_int("MXNET_ROUTER_MAX_INCIDENTS", 8)
        self._hops = _HopLog()
        self._federation: Dict[str, dict] = {}   # rep.id -> cached view
        self._federate_last = -1e9
        self._incident_lock = threading.Lock()
        self._incident_last: Dict[tuple, float] = {}
        self._incident_count = 0
        self._incident_seq = 0
        self._metrics_baseline: Dict[str, float] = {}
        self._baseline_time = time.time()
        self.last_incident_path: Optional[str] = None
        self._recorder: Optional[_ring.FlightRecorder] = None

    # -- registry -------------------------------------------------------
    @property
    def port(self) -> int:
        return self._port

    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    def replica(self, rid: str) -> Replica:
        for rep in self._replicas:
            if rep.id == rid:
                return rep
        raise KeyError(rid)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def total_inflight(self) -> int:
        return sum(r.inflight for r in self._replicas)

    # -- health loop ----------------------------------------------------
    def check_health_once(self) -> None:
        """One synchronous sweep over every replica (tests drive this
        directly; the background loop calls it on an interval)."""
        for rep in self._replicas:
            self._poll(rep)
        self._eligible()            # refresh the eligible-count gauge

    def _poll_timeout(self) -> float:
        return min(2.0, max(0.25, self.health_interval * 4.0))

    def _poll(self, rep: Replica) -> None:
        try:
            status, body = self._get_json(rep, "/readyz",
                                          self._poll_timeout())
        except OSError as e:
            rep.reachable = False
            rep.ready = False
            rep.last_error = f"health poll: {e}"
            self._record_failure(rep, "health poll failed")
            self._set_state_gauge(rep)
            return
        rep.reachable = True
        rep.ready = status == 200
        if isinstance(body, dict):
            rep.blockers = list(body.get("blockers") or [])
            if body.get("draining"):
                # the replica drains itself (SIGTERM / direct admin) —
                # treat like unready; the router-side drain flag is
                # only flipped by drain_replica()
                rep.ready = False
        rep.last_error = ""
        # a reachable replica is not a transport outlier, whatever its
        # readiness — close/feed the breaker with the success
        self._record_success(rep)
        if rep.ready:
            try:
                s, slo = self._get_json(rep, "/slo", self._poll_timeout())
                if s == 200 and isinstance(slo, dict):
                    models = slo.get("models", {})
                    burns = [m.get("burn_rate", 0.0)
                             for m in models.values()
                             if isinstance(m, dict)]
                    rep.burn = max(burns) if burns else 0.0
            except (OSError, ValueError):
                pass                # burn is advisory; keep the last view
        self._set_state_gauge(rep)

    def _set_state_gauge(self, rep: Replica) -> None:
        _m.ROUTER_REPLICA_STATE.set(rep.state_code(), replica=rep.id)

    def _record_success(self, rep: Replica) -> None:
        rep.breaker.record_success()

    def _record_failure(self, rep: Replica, reason: str) -> None:
        was_open = rep.breaker.state == _lc.OPEN
        rep.breaker.record_failure(reason)
        if not was_open and rep.breaker.state == _lc.OPEN:
            _m.ROUTER_EJECTIONS.inc(replica=rep.id)
            _telemetry.FAULT.publish(site="router.health",
                                     event="ejected", kind="breaker",
                                     replica=rep.id, reason=reason)
            self._incident("ejected", rep.id,
                           self._hops.request_ids_on(rep.id, failed=True))
        self._set_state_gauge(rep)

    def _health_run(self) -> None:
        while not self._stop.wait(self.health_interval):
            try:
                self.check_health_once()
            except Exception:       # the health loop must survive
                pass                # anything one replica throws at it
            try:
                self._federate_maybe()
            except Exception:
                pass

    # -- metrics federation ----------------------------------------------
    def _federate_maybe(self, force: bool = False) -> None:
        """Refresh the per-replica snapshot cache (``/metrics.json`` +
        ``/slo``) at the ``MXNET_ROUTER_FEDERATE_SECONDS`` cadence.
        Piggybacks on the health loop; also called on-demand by the
        federated ``GET /metrics``/``/slo`` so a router driven without
        the background loop (tests) still federates."""
        now = time.monotonic()
        if not force and now - self._federate_last < self.federate_seconds:
            return
        self._federate_last = now
        for rep in self._replicas:
            if not rep.reachable:
                continue            # last snapshot stays and ages out
            try:
                s, state = self._get_json(rep, "/metrics.json",
                                          self._poll_timeout())
                if s != 200 or not isinstance(state, dict):
                    continue
                s2, slo = self._get_json(rep, "/slo",
                                         self._poll_timeout())
            except OSError:
                continue
            entry = {"state": state,
                     "slo": slo if s2 == 200 and isinstance(slo, dict)
                     else None,
                     "time": time.monotonic(),
                     "time_unix": time.time()}
            with self._lock:
                self._federation[rep.id] = entry

    def _stale_horizon(self) -> float:
        return max(3.0 * self.federate_seconds, 1.0)

    @staticmethod
    def _strip_router_series(state: dict) -> dict:
        """Drop control-plane families (``mxtpu_router_*`` and the
        supervisor's ``mxtpu_supervise_*``/``mxtpu_autoscale_*``) from a
        replica snapshot.  Those series are rendered exactly once from
        the control process's local registry; a replica that happens to
        share a registry with a router (in-process tests) or fronts a
        nested router must not double-count them in fleet sums."""
        return {kind: {name: v for name, v in
                       (state or {}).get(kind, {}).items()
                       if not name.startswith(CONTROL_PLANE_PREFIXES)}
                for kind in ("counters", "gauges", "histograms")}

    def _federation_view(self):
        """``[(replica_id, entry, stale)]`` for every cached snapshot,
        refreshing the ``mxtpu_router_federation_stale`` gauge."""
        with self._lock:
            fed = dict(self._federation)
        now = time.monotonic()
        horizon = self._stale_horizon()
        out = [(rid, entry, now - entry["time"] > horizon)
               for rid, entry in sorted(fed.items())]
        _m.ROUTER_FEDERATION_STALE.set(sum(1 for _, _, s in out if s))
        return out

    def fleet_metrics_state(self) -> dict:
        """One mergeable state for the whole fleet: counters/gauges hold
        the fleet-sum label sets PLUS per-replica ``replica=``-labeled
        series (stale snapshots keep their series, tagged
        ``stale="true"``, but are excluded from the sums); histograms
        are the cross-replica reservoir union, so fleet quantiles come
        from merged distributions, not averaged percentiles."""
        view = self._federation_view()
        fresh = [self._strip_router_series(e["state"])
                 for _, e, stale in view if not stale]
        fleet = _telemetry.merge_states(fresh)
        for rid, entry, stale in view:
            state = self._strip_router_series(entry["state"])
            extra = f"replica={rid}" + (",stale=true" if stale else "")
            for kind in ("counters", "gauges"):
                for name, m in state.get(kind, {}).items():
                    dst = fleet[kind].setdefault(
                        name, {"help": m.get("help", ""), "values": {}})
                    total = sum(float(v) for v in
                                (m.get("values") or {}).values())
                    dst["values"][extra] = total
        return fleet

    def render_fleet_metrics(self) -> str:
        """The federated ``GET /metrics`` body: the control plane's own
        series (``mxtpu_router_*`` plus, when a supervisor shares the
        process, ``mxtpu_supervise_*``/``mxtpu_autoscale_*`` — local
        registry, rendered once) + fleet sums and per-replica series
        for everything the replicas report."""
        self._federate_maybe()
        local = _telemetry.registry.export_state()
        local = {kind: {name: v for name, v in local[kind].items()
                        if name.startswith(CONTROL_PLANE_PREFIXES)}
                 for kind in ("counters", "gauges", "histograms")}
        return _telemetry.render_prometheus_state(local) + \
            _telemetry.render_prometheus_state(self.fleet_metrics_state())

    def fleet_slo(self) -> dict:
        """The fleet ``GET /slo`` body: per-replica windows merged by
        summed counts (:func:`serving.slo.merge_snapshots`) — the burn a
        user sees through the router, not any one replica's view."""
        self._federate_maybe()
        view = self._federation_view()
        body = _slo.merge_snapshots(
            {rid: e.get("slo") for rid, e, stale in view if not stale})
        stale = [rid for rid, _, s in view if s]
        if stale:
            body["stale_replicas"] = stale
        return body

    # -- cross-process trace stitching ------------------------------------
    @staticmethod
    def _remote_parent_of(span: dict) -> Optional[str]:
        attrs = span.get("attrs") if isinstance(span, dict) else None
        return attrs.get("remote_parent") if isinstance(attrs, dict) \
            else None

    def stitch_trace(self, rid: str) -> Optional[dict]:
        """One end-to-end timeline for request ``rid``: the router's hop
        records (every upstream attempt, retries and failovers included)
        with each replica's remote span subtree grafted under the hop
        whose span id it names in its ``remote_parent`` attr.  A replica
        that can't answer ``/trace`` anymore (died mid-request) shows up
        as a synthetic ``unreachable`` span under its hop.  ``None``
        when the request id is unknown (aged out or never seen)."""
        hops = self._hops.get(rid)
        if not hops:
            return None
        remote: Dict[str, object] = {}
        for rep_id in sorted({h["replica"] for h in hops}):
            _m.ROUTER_TRACE_FANOUT.inc(replica=rep_id)
            try:
                rep = self.replica(rep_id)
                status, body = self._get_json(
                    rep, "/trace?request_id=" + quote(rid, safe=""),
                    self.upstream_timeout)
                if status == 200 and isinstance(body, dict):
                    remote[rep_id] = body.get("spans") or []
                else:
                    remote[rep_id] = OSError(f"/trace answered {status}")
            except (KeyError, OSError) as e:
                remote[rep_id] = e
        claimed = set()
        out_hops = []
        for h in hops:
            d = {"name": "router.hop", "cat": "router", "id": h["sid"],
                 "request_id": rid}
            d.update({k: v for k, v in h.items() if k != "sid"})
            spans = remote.get(h["replica"])
            if isinstance(spans, Exception):
                d["children"] = [{
                    "name": "unreachable", "cat": "router",
                    "synthetic": True, "replica": h["replica"],
                    "error": str(spans)[:200]}]
            else:
                kids = [s for s in (spans or [])
                        if self._remote_parent_of(s) == h["sid"]]
                claimed.update(id(s) for s in kids)
                if kids:
                    d["children"] = kids
            out_hops.append(d)
        out = {"request_id": rid, "trace_id": rid, "stitched": True,
               "hops": out_hops}
        unlinked = {rep_id: [s for s in spans if id(s) not in claimed]
                    for rep_id, spans in remote.items()
                    if isinstance(spans, list)}
        unlinked = {k: v for k, v in unlinked.items() if v}
        if unlinked:
            # spans that match the request id but name no known hop —
            # direct-to-replica traffic or a pre-propagation replica;
            # surfaced rather than dropped
            out["unlinked_spans"] = unlinked
        if _telemetry.tracer.active:
            router_spans = _telemetry.tracer.find_spans("request_id", rid)
            if router_spans:
                out["router_spans"] = router_spans
        return out

    # -- correlated incident bundles --------------------------------------
    def _incident(self, reason: str, replica_id: Optional[str],
                  request_ids: Sequence[str]) -> None:
        """Budgeted, debounced, async incident-bundle trigger — the
        router-side analogue of ``FlightRecorder._auto_dump``.  Debounce
        is per (reason, replica): one flapping replica costs one bundle
        per ``incident_debounce`` window, and the process writes at most
        ``MXNET_ROUTER_MAX_INCIDENTS`` bundles."""
        now = time.monotonic()
        key = (reason, replica_id or "")
        with self._incident_lock:
            if self._incident_count >= self.max_incidents:
                return
            if now - self._incident_last.get(key, -1e9) < \
                    self.incident_debounce:
                return
            self._incident_last[key] = now
            self._incident_count += 1
            self._incident_seq += 1
            seq = self._incident_seq
        threading.Thread(
            target=self._write_incident_guarded,
            args=(reason, replica_id, list(request_ids or ()), seq),
            name="mxtpu-router-incident", daemon=True).start()

    def _write_incident_guarded(self, reason, replica_id, request_ids,
                                seq) -> None:
        try:
            self.write_incident(reason, replica_id, request_ids, seq)
        except Exception:           # the observer must never take
            pass                    # the router down

    def _fleet_counters_flat(self) -> Dict[str, float]:
        """name → fleet-total for every counter (fresh replicas + the
        router's own ``mxtpu_router_*``) — the incident bundle's metrics
        delta is computed against this."""
        out: Dict[str, float] = {}
        for _, entry, stale in self._federation_view():
            if stale:
                continue
            state = self._strip_router_series(entry["state"])
            for name, m in state.get("counters", {}).items():
                out[name] = out.get(name, 0.0) + sum(
                    float(v) for v in (m.get("values") or {}).values())
        for name, m in _telemetry.registry.export_state()[
                "counters"].items():
            if name.startswith("mxtpu_router_"):
                out[name] = sum(float(v) for v in
                                (m.get("values") or {}).values())
        return out

    def write_incident(self, reason: str, replica_id: Optional[str],
                       request_ids: Sequence[str],
                       seq: Optional[int] = None) -> str:
        """Write one atomic incident bundle directory and return its
        path: the router's flight-recorder payload, the implicated
        replica's ring (``GET /flight``) and recent spans, the stitched
        traces for the request ids that observed the failure, and the
        fleet metrics delta since the router's baseline — all
        cross-keyed by request id in ``incident.json``.  Atomicity:
        assembled under a dot-tmp name, ``os.rename``d into place, so a
        reader never sees a half-written bundle."""
        request_ids = [str(r) for r in (request_ids or ())][:8]
        if seq is None:
            with self._incident_lock:
                self._incident_seq += 1
                seq = self._incident_seq
        base = self.incident_dir
        os.makedirs(base, exist_ok=True)
        name = f"incident_{os.getpid()}_{seq:03d}_{reason}"
        tmp = os.path.join(base, f".{name}.tmp-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)

        def _write(fname, payload):
            with open(os.path.join(tmp, fname), "w") as f:
                json.dump(payload, f, indent=2, default=str)
                f.write("\n")
            return fname

        files = [_write("router_flight.json",
                        _ring.recorder.payload(f"incident:{reason}"))]
        if replica_id:
            safe = replica_id.replace(":", "_")
            try:
                rep = self.replica(replica_id)
                _, flight = self._get_json(rep, "/flight",
                                           self.upstream_timeout)
            except (KeyError, OSError) as e:
                flight = {"unreachable": True, "error": str(e)[:200]}
            files.append(_write(f"replica_{safe}_flight.json", flight))
            traces = {}
            for rid in request_ids:
                try:
                    rep = self.replica(replica_id)
                    _, traces[rid] = self._get_json(
                        rep,
                        "/trace?request_id=" + quote(rid, safe=""),
                        self.upstream_timeout)
                except (KeyError, OSError) as e:
                    traces[rid] = {"unreachable": True,
                                   "error": str(e)[:200]}
            files.append(_write(f"replica_{safe}_trace.json",
                                {"replica": replica_id,
                                 "request_ids": traces}))
        files.append(_write(
            "stitched_traces.json",
            {rid: self.stitch_trace(rid) for rid in request_ids}))
        current = self._fleet_counters_flat()
        delta = {k: v - self._metrics_baseline.get(k, 0.0)
                 for k, v in sorted(current.items())
                 if v - self._metrics_baseline.get(k, 0.0) != 0.0}
        files.append(_write("metrics_delta.json", {
            "since_unix": round(self._baseline_time, 3),
            "window_seconds": round(
                time.time() - self._baseline_time, 3),
            "counters_delta": delta}))
        _write("incident.json", {
            "reason": reason,
            "time_unix": round(time.time(), 3),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "replica": replica_id,
            "request_ids": request_ids,
            "replicas": [r.snapshot() for r in self._replicas],
            "files": files,
        })
        final = os.path.join(base, name)
        os.rename(tmp, final)       # readers never see a torn bundle
        self.last_incident_path = final
        _m.ROUTER_INCIDENTS.inc(reason=reason)
        _telemetry.FAULT.publish(site="router.incident", event="bundle",
                                 kind=reason, replica=replica_id or "",
                                 path=final)
        return final

    # -- routing --------------------------------------------------------
    def _eligible(self) -> List[Replica]:
        now = time.monotonic()
        out = [r for r in self._replicas if r.eligible(now)]
        _m.ROUTER_REPLICAS_ELIGIBLE.set(len(out))
        return out

    @staticmethod
    def _load_score(rep: Replica) -> float:
        return (rep.inflight + 1.0) * (1.0 + max(0.0, rep.burn))

    def route(self, affinity_key: Optional[bytes] = None,
              exclude=()) -> Replica:
        """Pick the replica for one upstream attempt.  ``exclude``
        holds replica ids already tried this request — preferred
        avoided, reused only when nothing else is eligible."""
        pool = self._eligible()
        if not pool:
            raise NoReplicaAvailable(
                "no eligible replica (states: "
                + ", ".join(f"{r.id}={r.snapshot()['state']}"
                            for r in self._replicas) + ")",
                retry_after=min(1.0, max(0.05, self.health_interval)))
        fresh = [r for r in pool if r.id not in exclude] or pool
        if affinity_key is not None and self.affinity:
            ranked = rendezvous_order(affinity_key, fresh)
            floor = min(r.inflight for r in fresh)
            for i, rep in enumerate(ranked):
                if rep.inflight - floor <= self.spill_margin:
                    if i == 0:
                        _m.ROUTER_AFFINITY.inc(replica=rep.id)
                    else:
                        _m.ROUTER_SPILLS.inc(replica=rep.id)
                    return rep
            _m.ROUTER_SPILLS.inc(replica=ranked[-1].id)
            return min(ranked, key=self._load_score)
        with self._lock:
            self._rr += 1
            start = self._rr % len(fresh)
        rotated = fresh[start:] + fresh[:start]
        return min(rotated, key=self._load_score)

    # -- upstream transport ---------------------------------------------
    def _connect(self, rep: Replica,
                 timeout: Optional[float] = None
                 ) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(
            rep.host, rep.port,
            timeout=self.upstream_timeout if timeout is None
            else timeout)

    def _get_json(self, rep: Replica, path: str,
                  timeout: float) -> Tuple[int, dict]:
        conn = self._connect(rep, timeout)
        try:
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                data = resp.read()
            except http.client.HTTPException as e:
                raise ConnectionError(str(e)) from e
            try:
                body = json.loads(data.decode("utf-8")) if data else {}
            except (ValueError, UnicodeDecodeError):
                body = {}
            return resp.status, body
        finally:
            conn.close()

    @staticmethod
    def _retry_after_of(resp, body: Optional[dict]) -> Optional[float]:
        raw = resp.getheader("Retry-After")
        if raw is None and isinstance(body, dict):
            raw = body.get("retry_after")
        try:
            return max(0.0, float(raw)) if raw is not None else None
        except (TypeError, ValueError):
            return None

    def _has_alternative(self, tried) -> bool:
        return any(r.id not in tried for r in self._eligible())

    # -- the proxy core --------------------------------------------------
    def proxy(self, handler: BaseJSONHandler, path: str, body: bytes,
              rid: str, affinity_key: Optional[bytes] = None,
              stream: bool = False) -> None:
        """Forward one ``:predict``/``:generate`` POST, retrying with
        failover, then relay the terminal response (or the SSE stream)
        to ``handler``."""
        _m.ROUTER_REQUESTS.inc()
        if self._draining:
            handler.send_json(
                503, {"error": "router is draining", "request_id": rid},
                headers={"Retry-After": 1})
            return
        tried: List[str] = []

        def attempt():
            rep = self.route(affinity_key=affinity_key, exclude=tried)
            tried.append(rep.id)
            if len(tried) > 1:
                _m.ROUTER_RETRIES.inc(replica=rep.id)
            t0 = time.monotonic()
            try:
                _fault.inject(FAULT_SITE, replica=rep.id,
                              request_id=rid)
                return self._dispatch(rep, path, body, rid, stream)
            finally:
                _m.ROUTER_UPSTREAM.observe(time.monotonic() - t0)

        try:
            result = _fault.retry_call(
                attempt, site=FAULT_SITE,
                policy=_fault.RetryPolicy(
                    max_retries=self.retries, base_seconds=0.05,
                    deadline_seconds=self.retry_deadline),
                retry_on=(UpstreamError, OSError),
                retry_after_hint=_fault.retry_after_hint)
        except (UpstreamError, OSError) as e:
            retry = getattr(e, "retry_after", None)
            self._incident("failover_exhausted",
                           getattr(e, "replica", None)
                           or (tried[-1] if tried else None), [rid])
            handler.send_json(
                503, {"error": f"no replica could serve the request: "
                               f"{e}", "request_id": rid,
                      "replicas_tried": tried},
                headers={"Retry-After": retry if retry else 1})
            return
        if len(set(tried)) > 1:
            _m.ROUTER_FAILOVERS.inc()
        if result[0] == "json":
            _, status, data, headers = result
            handler._send(status, data,
                          headers.pop("content-type",
                                      "application/json"),
                          headers=headers or None)
        else:
            _, rep, conn, resp, head, hop = result
            self._relay_stream(handler, rep, conn, resp, head, rid, hop)

    def _dispatch(self, rep: Replica, path: str, body: bytes, rid: str,
                  stream: bool):
        """One upstream attempt.  Returns ``("json", status, text,
        headers)`` for terminal responses or ``("stream", rep, conn,
        resp, head)`` once an SSE stream has produced its first event.
        Raises :class:`UpstreamError` (or ``OSError``) for anything
        worth failing over."""
        rep._inflight_add(+1)
        hop = self._hops.begin(rid, rep.id)
        conn = self._connect(rep)
        done = False
        try:
            try:
                conn.request(
                    "POST", path, body=body,
                    headers={"Content-Type": "application/json",
                             "X-Request-Id": rid,
                             # traceparent: <trace root>-<hop span id> —
                             # the replica's serve.request span records
                             # both, so the stitcher can graft it under
                             # exactly this attempt
                             "X-Trace-Id": f"{rid}-{hop['sid']}",
                             "Accept": "text/event-stream" if stream
                             else "application/json"})
                resp = conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                # BadStatusLine/ConnectionReset both mean the same
                # thing here: the replica's socket is gone
                rep.reachable = False
                rep.last_error = str(e)
                self._hops.end(hop, "connect_error", error=e)
                self._record_failure(rep, f"connect: {e}")
                raise UpstreamError(
                    f"{rep.id}: {e}", replica=rep.id,
                    retry_after=0.0 if self._has_alternative([rep.id])
                    else None) from e
            if resp.status in (429, 503):
                data = resp.read()
                try:
                    parsed = json.loads(data.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    parsed = {}
                retry = self._retry_after_of(resp, parsed)
                if retry is not None:
                    rep.note_backoff(retry)
                if resp.status == 503:
                    # shedding (drain/breaker/abort) — readiness will
                    # reflect it on the next poll; not a transport fault
                    rep.ready = False
                self._set_state_gauge(rep)
                self._hops.end(hop, "shed", status=resp.status)
                raise UpstreamError(
                    f"{rep.id} answered {resp.status}", replica=rep.id,
                    retry_after=0.0 if self._has_alternative([rep.id])
                    else retry)
            if stream and resp.status == 200 and "text/event-stream" in \
                    (resp.getheader("Content-Type") or ""):
                head = b""
                while b"\n\n" not in head:
                    try:
                        chunk = resp.read1(65536)
                    except (OSError,
                            http.client.HTTPException) as e:
                        chunk = b""     # IncompleteRead == dead socket
                        rep.last_error = str(e)
                    if not chunk:
                        # died before the FIRST event: nothing reached
                        # the client, failover is transparent
                        self._hops.end(hop, "stream_died_before_first",
                                       error=rep.last_error or None)
                        self._record_failure(
                            rep, "stream died before first event")
                        raise UpstreamError(
                            f"{rep.id} closed the stream before the "
                            "first event", replica=rep.id,
                            retry_after=0.0
                            if self._has_alternative([rep.id])
                            else None)
                    head += chunk
                self._record_success(rep)
                done = True         # inflight stays held for the relay
                return ("stream", rep, conn, resp, head, hop)
            try:
                data = resp.read().decode("utf-8", "replace")
            except (OSError, http.client.HTTPException) as e:
                self._hops.end(hop, "body_read_error", error=e)
                self._record_failure(rep, f"body read: {e}")
                raise UpstreamError(
                    f"{rep.id} died mid-response: {e}", replica=rep.id,
                    retry_after=0.0 if self._has_alternative([rep.id])
                    else None) from e
            headers = {k: resp.getheader(k) for k in _HOP_HEADERS
                       if resp.getheader(k) is not None}
            if resp.status < 500:
                self._record_success(rep)
            self._hops.end(hop, "ok" if resp.status < 500
                           else "upstream_error", status=resp.status)
            return ("json", resp.status, data, headers)
        finally:
            if not done:
                rep._inflight_add(-1)
                conn.close()

    def _relay_stream(self, handler: BaseJSONHandler, rep: Replica,
                      conn, resp, head: bytes, rid: str,
                      hop: Optional[dict] = None) -> None:
        """Relay an open upstream SSE stream.  Downstream disconnect →
        close upstream (the replica cancels and frees its slot/blocks).
        Upstream EOF without a terminal ``done``/``error`` event →
        terminal SSE ``error`` event with the request id."""
        terminal = any(mark in head for mark in _TERMINAL_MARKS)
        tail = head[-64:]
        outcome = "client_disconnect"
        try:
            handler.start_stream(200)
            try:
                handler.relay_chunk(head)
            except OSError:
                return              # client gone → finally closes conn
            if conn.sock is not None:
                conn.sock.settimeout(self.stream_timeout)
            while True:
                try:
                    chunk = resp.read1(65536)
                except (OSError, http.client.HTTPException) as e:
                    rep.last_error = str(e)
                    chunk = b""
                if not chunk:
                    break
                window = tail + chunk
                if any(mark in window for mark in _TERMINAL_MARKS):
                    terminal = True
                tail = window[-64:]
                try:
                    handler.relay_chunk(chunk)
                except OSError:
                    return          # client disconnect mid-stream
            if terminal:            # done/error already on the wire —
                outcome = "ok"
                try:                # a late reset changes nothing
                    handler.end_stream()
                except OSError:
                    pass
                return
            # mid-stream death with tokens already on the wire: the
            # stream cannot be transparently replayed — fail loudly
            outcome = "midstream_error"
            _m.ROUTER_STREAM_ERRORS.inc(replica=rep.id)
            self._record_failure(rep, "mid-stream death")
            _telemetry.FAULT.publish(site=FAULT_SITE,
                                     event="stream_error",
                                     kind="midstream", replica=rep.id,
                                     request_id=rid)
            try:
                handler.send_event(
                    {"error": f"replica {rep.id} died mid-stream",
                     "request_id": rid, "replica": rep.id},
                    event="error")
                handler.end_stream()
            except OSError:
                pass
        finally:
            if hop is not None:
                self._hops.end(hop, outcome)
            rep._inflight_add(-1)
            conn.close()

    # -- GET passthrough (registry/SLO views) ----------------------------
    def forward_get(self, handler: BaseJSONHandler, path: str) -> None:
        for rep in self._eligible():
            try:
                status, body = self._get_json(rep, path,
                                              self.upstream_timeout)
            except OSError:
                continue
            handler.send_json(status, body)
            return
        handler.send_json(503, {"error": "no eligible replica"},
                          headers={"Retry-After": 1})

    def fanout_get(self, path: str,
                   timeout: Optional[float] = None) -> dict:
        """GET ``path`` on EVERY eligible replica in parallel and return
        ``{replica_id: body}`` — the fleet view behind the router's
        ``/programs`` and ``/memory`` routes (one replica's answer is
        not the fleet's: program sets and memory are per-process)."""
        reps = self._eligible()
        out: dict = {}

        def one(rep):
            try:
                status, body = self._get_json(
                    rep, path,
                    self.upstream_timeout if timeout is None
                    else timeout)
                out[rep.id] = body if status == 200 \
                    else {"error": f"HTTP {status}", "status": status}
            except OSError as e:
                out[rep.id] = {"error": str(e)}

        threads = [threading.Thread(target=one, args=(r,), daemon=True)
                   for r in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def fleet_health(self) -> dict:
        """``GET /health`` federation: every replica's health-plane
        report plus a worst-replica rollup — ``status`` is anomalous if
        ANY replica is, ``fleet_anomaly_total`` sums the per-replica
        counts, and ``worst`` names the replica with the most anomalies
        (its last anomaly inlined) so one request answers "is any
        replica's numerics going sideways, and which one"."""
        replicas = self.fanout_get("/health")
        total = 0.0
        worst_id, worst_count, worst_last = None, -1.0, None
        for rid, body in replicas.items():
            if not isinstance(body, dict) or "error" in body:
                continue
            count = float(body.get("anomaly_total", 0.0) or 0.0)
            total += count
            if count > worst_count:
                worst_id, worst_count = rid, count
                worst_last = body.get("last_anomaly")
        out = {
            "status": "anomalous" if total else "ok",
            "fleet_anomaly_total": total,
            "replicas": replicas,
        }
        if worst_id is not None:
            out["worst"] = {"replica": worst_id,
                            "anomaly_total": max(worst_count, 0.0),
                            "last_anomaly": worst_last}
        return out

    def profile_fanout(self, seconds: float) -> dict:
        """``POST /debug/profile`` fan-out: trigger one on-demand
        profiler capture on every eligible replica in parallel and
        collect the per-replica artifact paths.  Replica-side capture
        blocks for the window plus profiler startup and trace
        serialization (the FIRST capture in a process costs seconds on
        its own), so the upstream timeout is the window plus a generous
        margin — never the router's default."""
        reps = self._eligible()
        results: dict = {}
        timeout = float(seconds) + max(30.0, 2.0 * float(seconds))

        def one(rep):
            conn = self._connect(rep, timeout)
            try:
                try:
                    conn.request(
                        "POST", f"/debug/profile?seconds={seconds}",
                        body=b"{}",
                        headers={"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    data = resp.read()
                except (OSError, http.client.HTTPException) as e:
                    results[rep.id] = {"error": str(e)}
                    return
                try:
                    body = json.loads(data.decode("utf-8")) \
                        if data else {}
                except (ValueError, UnicodeDecodeError):
                    body = {}
                if resp.status != 200:
                    body.setdefault("error", f"HTTP {resp.status}")
                    body["status"] = resp.status
                results[rep.id] = body
            finally:
                conn.close()

        threads = [threading.Thread(target=one, args=(r,), daemon=True)
                   for r in reps]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {"seconds": float(seconds), "replicas": results}

    # -- drain orchestration ---------------------------------------------
    def _admin(self, rep: Replica, path: str) -> None:
        conn = self._connect(rep)
        try:
            conn.request("POST", path, body=b"{}",
                         headers={"Content-Type": "application/json"})
            conn.getresponse().read()
        finally:
            conn.close()

    def drain_replica(self, rid: str,
                      wait_seconds: Optional[float] = None) -> dict:
        """Zero-downtime drain of one replica: stop routing to it
        FIRST, then forward the drain (its own ``/readyz`` flips for
        any other balancer), then wait for the router's in-flight
        count on it to reach zero."""
        rep = self.replica(rid)     # KeyError → HTTP 404
        rep.draining = True
        self._set_state_gauge(rep)
        _telemetry.FAULT.publish(site="router.admin", event="drain",
                                 kind="begin", replica=rep.id)
        try:
            self._admin(rep, "/admin/drain")
        except OSError as e:        # already dead — drained by definition
            rep.last_error = str(e)
        if wait_seconds is None:
            wait_seconds = _lc.default_drain_seconds()
        deadline = time.monotonic() + max(0.0, float(wait_seconds))
        while rep.inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        left = rep.inflight
        if left > 0:
            # requests wedged past the drain budget: capture both sides
            # while the replica can still answer /flight and /trace
            self._incident("drain_timeout", rep.id,
                           self._hops.request_ids_on(rep.id,
                                                     failed=True))
        return {"replica": rep.id, "draining": True,
                "drained": left == 0, "inflight": left}

    def undrain_replica(self, rid: str) -> dict:
        """Reverse :meth:`drain_replica` and re-poll health so the
        replica rejoins the eligible set immediately."""
        rep = self.replica(rid)
        try:
            self._admin(rep, "/admin/undrain")
        except OSError as e:
            rep.last_error = str(e)
        rep.draining = False
        self._poll(rep)
        _telemetry.FAULT.publish(site="router.admin", event="drain",
                                 kind="end", replica=rep.id)
        return {"replica": rep.id, "draining": False,
                "eligible": rep.eligible()}

    # -- dynamic membership ----------------------------------------------
    def add_replica(self, spec: str) -> dict:
        """Join ``spec`` (``host:port``) to the fleet at runtime
        (``POST /admin/replicas``).  Idempotent — re-adding a member is
        a no-op, so a supervisor can retry registration blindly.  The
        newcomer is polled synchronously before this returns: it enters
        the routing tables with a real health verdict, and rendezvous
        hashing keeps the prefix-affinity remap to ~1/N keys."""
        rep = Replica(spec, eject_threshold=self.eject_threshold,
                      eject_cooldown_seconds=self.eject_cooldown_seconds)
        with self._lock:
            existing = next((r for r in self._replicas
                             if r.id == rep.id), None)
            if existing is None:
                # copy-on-write: readers iterate the old list lock-free
                self._replicas = self._replicas + [rep]
        if existing is not None:
            return {"replica": existing.id, "added": False,
                    "eligible": existing.eligible(),
                    "replicas": len(self._replicas)}
        self._poll(rep)             # route with a view, not a guess
        _m.ROUTER_MEMBERSHIP.inc(action="join")
        _telemetry.FAULT.publish(site="router.admin", event="membership",
                                 kind="join", replica=rep.id)
        return {"replica": rep.id, "added": True,
                "eligible": rep.eligible(),
                "replicas": len(self._replicas)}

    def remove_replica(self, rid: str,
                       wait_seconds: Optional[float] = None,
                       drain: bool = True) -> dict:
        """Leave the fleet (``DELETE /admin/replicas/<id>``):
        drain-then-remove, so membership changes are zero-downtime by
        construction.  ``drain=False`` skips the drain for a member
        that is already dead (a supervisor removing a quarantined
        corpse has nothing to wait for)."""
        rep = self.replica(rid)     # KeyError → HTTP 404
        drained = None
        if drain:
            drained = self.drain_replica(rid, wait_seconds=wait_seconds)
        with self._lock:
            self._replicas = [r for r in self._replicas if r.id != rid]
            self._federation.pop(rid, None)
        _m.ROUTER_MEMBERSHIP.inc(action="leave")
        _telemetry.FAULT.publish(site="router.admin", event="membership",
                                 kind="leave", replica=rep.id)
        out = {"replica": rep.id, "removed": True,
               "replicas": len(self._replicas)}
        if drained is not None:
            out["drained"] = drained["drained"]
            out["inflight"] = drained["inflight"]
        return out

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Router":
        if self._http is not None:
            return self
        srv = _RouterHTTPServer((self._host, self._port), _RouterHandler)
        srv.router = self
        self._port = srv.server_address[1]
        self._stop.clear()
        th = threading.Thread(target=srv.serve_forever,
                              name="mxtpu-router-http", daemon=True)
        th.start()
        self._http, self._http_thread = srv, th
        # the router is an incident witness: its flight ring records
        # FAULT events (ejections, stream errors) and the provider adds
        # the fleet view + recent hops to every dump/bundle
        self._recorder = _ring.recorder
        self._recorder.start()
        self._recorder.register_provider("router", self._flight_state)
        self.check_health_once()    # serve with a view, not a guess
        self._federate_maybe(force=True)
        self._metrics_baseline = self._fleet_counters_flat()
        self._baseline_time = time.time()
        self._health_thread = threading.Thread(
            target=self._health_run, name="mxtpu-router-health",
            daemon=True)
        self._health_thread.start()
        return self

    def _flight_state(self) -> dict:
        return {"draining": self._draining,
                "replicas": [r.snapshot() for r in self._replicas],
                "recent_hops": self._hops.recent(32)}

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        th, self._health_thread = self._health_thread, None
        if th is not None:
            th.join(timeout=timeout)
        srv, self._http = self._http, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=timeout)
            self._http_thread = None
        rec, self._recorder = self._recorder, None
        if rec is not None:
            rec.unregister_provider("router")
            rec.stop()

    def shutdown(self, drain_seconds: Optional[float] = None) -> None:
        """The SIGTERM sequence (``run_until_shutdown``): refuse new
        work (503 + ``Retry-After``), let in-flight requests finish
        within the drain budget, then close the port."""
        self._draining = True
        if drain_seconds is None:
            drain_seconds = _lc.default_drain_seconds()
        deadline = time.monotonic() + max(0.0, float(drain_seconds))
        while self.total_inflight > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        self.stop()

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class _RouterHandler(BaseJSONHandler):
    server_version = "mxtpu-router/1.0"

    def do_GET(self):   # noqa: N802 (http.server API)
        self.guard(self._get)

    def do_POST(self):  # noqa: N802
        self.guard(self._post)

    def do_DELETE(self):  # noqa: N802
        self.guard(self._delete)

    def _delete(self):
        from urllib.parse import parse_qs, urlsplit
        router: Router = self.server.router
        split = urlsplit(self.path)
        params = parse_qs(split.query)
        path = split.path.rstrip("/")
        if not path.startswith("/admin/replicas/"):
            self.send_text(404,
                           "not found: DELETE /admin/replicas/<id>\n")
            return
        rid = path[len("/admin/replicas/"):]
        drain = params.get("drain", ["1"])[-1] not in ("0", "false")
        wait = params.get("wait_seconds")
        try:
            wait_seconds = float(wait[-1]) if wait else None
        except ValueError:
            self.send_json(400, {"error":
                                 "wait_seconds must be a number"})
            return
        try:
            out = router.remove_replica(rid, wait_seconds=wait_seconds,
                                        drain=drain)
        except KeyError:
            self.send_json(404, {
                "error": f"unknown replica {rid!r}",
                "replicas": [r.id for r in router.replicas]})
            return
        self.send_json(200, out)

    def _get(self):
        from urllib.parse import parse_qs, urlsplit
        router: Router = self.server.router
        split = urlsplit(self.path)
        params = parse_qs(split.query)
        path = split.path.rstrip("/") or "/"
        if path == "/healthz":
            self.send_json(200, {"status": "ok",
                                 "replicas": len(router.replicas)})
        elif path == "/readyz":
            eligible = len(router._eligible())
            ready = eligible > 0 and not router.draining
            body = {"status": "ready" if ready else
                    ("draining" if router.draining else "unready"),
                    "eligible": eligible,
                    "replicas": {r.id: r.snapshot()["state"]
                                 for r in router.replicas}}
            self.send_json(200 if ready else 503, body,
                           headers=None if ready else {"Retry-After": 1})
        elif path == "/replicas":
            self.send_json(200, {"replicas": [r.snapshot()
                                              for r in router.replicas]})
        elif path == "/v1/models":
            router.forward_get(self, path)
        elif path in ("/programs", "/memory"):
            # per-replica fan-out: program sets and device memory are
            # per-process facts — no single replica speaks for the fleet
            self.send_json(200, {"replicas": router.fanout_get(path)})
        elif path == "/slo":
            self.send_json(200, router.fleet_slo())
        elif path == "/health":
            # health-plane federation: per-replica reports plus the
            # worst-replica rollup (anomaly counts are per-process)
            self.send_json(200, router.fleet_health())
        elif path == "/trace":
            vals = params.get("request_id")
            rid = vals[-1] if vals else None
            if not rid:
                self.send_json(400, {
                    "error": "expected /trace?request_id=<rid>"})
                return
            body = router.stitch_trace(rid)
            if body is None:
                self.send_json(404, {
                    "error": f"no hops recorded for request {rid!r}",
                    "request_id": rid})
                return
            self.send_json(200, body)
        elif path in ("/metrics", "/"):
            self._send(200, router.render_fleet_metrics(),
                       "text/plain; version=0.0.4; charset=utf-8")
        else:
            self.send_text(404, "not found: try /v1/models /healthz "
                                "/readyz /replicas /metrics /slo "
                                "/health /programs /memory "
                                "/trace?request_id=<rid>\n")

    def _post(self):
        router: Router = self.server.router
        path = self.path.split("?", 1)[0]
        rid = self.request_id()
        if path == "/debug/profile":
            # fan the capture out to every eligible replica and return
            # one artifact path per replica (each replica enforces its
            # own single-capture guard — a busy one answers 409 inline)
            from urllib.parse import parse_qs, urlsplit
            params = parse_qs(urlsplit(self.path).query)
            try:
                seconds = float(params.get("seconds", ["1.0"])[0])
            except ValueError:
                self.send_json(400, {"error":
                                     "seconds must be a number"})
                return
            self.send_json(200, router.profile_fanout(seconds))
            return
        if path == "/admin/replicas":
            try:
                body = self.read_json()
            except ValueError as e:
                self.send_json(400, {"error": str(e)})
                return
            spec = body.get("replica") if isinstance(body, dict) \
                else None
            if not spec:
                self.send_json(400, {
                    "error": 'expected {"replica": "host:port"}',
                    "replicas": [r.id for r in router.replicas]})
                return
            try:
                out = router.add_replica(str(spec))
            except MXNetError as e:    # unparseable host:port
                self.send_json(400, {"error": str(e)})
                return
            self.send_json(200, out)
            return
        if path in ("/admin/drain", "/admin/undrain"):
            try:
                body = self.read_json()
            except ValueError as e:
                self.send_json(400, {"error": str(e)})
                return
            target = body.get("replica") if isinstance(body, dict) \
                else None
            if not target:
                self.send_json(400, {
                    "error": 'expected {"replica": "host:port"}',
                    "replicas": [r.id for r in router.replicas]})
                return
            try:
                if path == "/admin/drain":
                    out = router.drain_replica(
                        target, wait_seconds=body.get("wait_seconds"))
                else:
                    out = router.undrain_replica(target)
            except KeyError:
                self.send_json(404, {
                    "error": f"unknown replica {target!r}",
                    "replicas": [r.id for r in router.replicas]})
                return
            self.send_json(200, out)
            return
        if not path.startswith("/v1/models/") or ":" not in path:
            self.send_text(404,
                           "not found: POST /v1/models/<name>:predict "
                           "or :generate\n")
            return
        verb = path.rpartition(":")[2]
        body = self.read_body()
        stream, affinity_key = False, None
        if verb == "generate":
            try:
                payload = json.loads(body.decode("utf-8")) if body \
                    else {}
            except (ValueError, UnicodeDecodeError):
                payload = {}
            if isinstance(payload, dict):
                stream = bool(payload.get("stream", False))
                tokens = payload.get("tokens", payload.get("inputs"))
                if isinstance(tokens, (list, tuple)) \
                        and len(tokens) == 1 \
                        and isinstance(tokens[0], (list, tuple)):
                    tokens = tokens[0]
                if isinstance(tokens, (list, tuple)):
                    try:
                        affinity_key = prefix_key(
                            [int(t) for t in tokens],
                            router.block_size,
                            router.affinity_blocks)
                    except (TypeError, ValueError):
                        affinity_key = None
        router.proxy(self, path, body, rid,
                     affinity_key=affinity_key, stream=stream)
