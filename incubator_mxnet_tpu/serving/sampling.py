"""Sampling plane — in-program stochastic decoding for the serving
stack (docs/serving.md "Sampling").

The generation programs in :mod:`serving.engine` are a CLOSED compiled
set; sampling must not reopen it.  Everything here is therefore either
a **traced operand** of the existing programs (per-slot temperature /
top-k / top-p / logit-bias row / RNG root key — data, never shape) or
pure host-side bookkeeping (stop sequences, constrained-output masks).

Determinism is the whole design.  Each slot carries a *root* RNG key
derived from the request seed; the key that samples the token at
sequence position ``t`` is ``step_keys(root, t)`` — the position XORed
into the root's low word — computed in-program from the position
operand (the position IS the per-step key stream: the burst scan's
position carry advances it step by step).
Because the key depends only on ``(root, position)`` — never on which
program produced the logits — the per-step decode, the scanned burst,
and the speculative verify all draw the SAME gumbel noise for the same
position, which is what makes seeded runs bit-identical across every
dispatch path and at any speculative accept rate (the Gumbel-coupled
acceptance argument in ``GenerationEngine.spec_step``).

Sampling itself is branchless keyed Gumbel-max: filter the biased
logits to the top-k/top-p support, add gumbel noise from the position
key, argmax.  ``temperature == 0`` selects the plain biased argmax via
``jnp.where``, so the greedy path emits bit-identical tokens to the
pre-sampling programs while compiling to the same program set.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

import numpy as _np

from ..base import MXNetError

__all__ = ["SamplingParams", "root_key", "derive_candidate_seed",
           "step_keys", "sample_tokens", "topn_logprobs", "stop_trim",
           "JsonMaskMachine", "MASK_OFF"]

# Disallowed tokens get this logit bias: decisively below any real
# logit, but finite — a fully-masked row must degrade to a defined
# argmax, never a NaN softmax (-inf - -inf) inside a compiled program.
MASK_OFF = -1e9


# ---------------------------------------------------------------------------
# request-level parameters
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.  The default instance is
    exactly the pre-sampling greedy contract: ``temperature == 0``
    decodes argmax, every other field inert.

    ``stop`` is a tuple of token-id sequences (the serving API speaks
    token ids); detection happens host-side at the emit boundary, and
    the matched stop sequence itself stays in the output.  ``seed``
    None + ``temperature > 0`` means the server picks (and echoes) one
    — a sampled response is always replayable."""

    temperature: float = 0.0
    top_k: int = 0                  # 0: no top-k filter
    top_p: float = 1.0              # 1.0: no nucleus filter
    seed: Optional[int] = None
    logprobs: int = 0               # top-N per-token logprobs (0: off)
    stop: Tuple[Tuple[int, ...], ...] = ()
    n: int = 1                      # candidate fan-out over slots
    logit_bias: Optional[Dict[int, float]] = None
    json_mode: bool = False

    @property
    def sampled(self) -> bool:
        return float(self.temperature) > 0.0

    def validate(self, *, max_stops: int = 4, max_stop_len: int = 16,
                 max_n: int = 8) -> "SamplingParams":
        """Range-check every field (ValueError → HTTP 400) and return
        a canonicalized copy (stop sequences as int tuples)."""
        if not float(self.temperature) >= 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if int(self.top_k) < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < float(self.top_p) <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")
        if self.seed is not None and not 0 <= int(self.seed) < 2 ** 63:
            raise ValueError(f"seed must be in [0, 2**63), got "
                             f"{self.seed}")
        if int(self.logprobs) < 0:
            raise ValueError(
                f"logprobs must be >= 0, got {self.logprobs}")
        stops = []
        for s in self.stop or ():
            seq = tuple(int(t) for t in
                        (s if isinstance(s, (list, tuple)) else (s,)))
            if not seq:
                raise ValueError("stop sequences must be non-empty")
            if len(seq) > int(max_stop_len):
                raise ValueError(
                    f"stop sequence length {len(seq)} exceeds "
                    f"{max_stop_len}")
            stops.append(seq)
        if len(stops) > int(max_stops):
            raise ValueError(
                f"{len(stops)} stop sequences exceed the limit of "
                f"{max_stops} (MXNET_SAMPLING_MAX_STOPS)")
        if not 1 <= int(self.n) <= int(max_n):
            raise ValueError(f"n must be in [1, {max_n}], got {self.n}")
        if self.logit_bias:
            for t, b in self.logit_bias.items():
                int(t), float(b)    # TypeError/ValueError → HTTP 400
        return replace(self, temperature=float(self.temperature),
                       top_k=int(self.top_k), top_p=float(self.top_p),
                       logprobs=int(self.logprobs), n=int(self.n),
                       stop=tuple(stops))


def root_key(seed: int) -> _np.ndarray:
    """The slot's RNG root as a host uint32 pair — bit-identical to
    ``jax.random.PRNGKey(seed)`` (legacy threefry seeding) without a
    device dispatch.  PRNGKey derives the high word from the seed's
    upper 32 bits only under ``jax_enable_x64``; replicating that keeps
    the replay contract exact either way."""
    import jax
    s = int(seed) & ((1 << 64) - 1)
    high = (s >> 32) & 0xFFFFFFFF if jax.config.jax_enable_x64 else 0
    return _np.array([high, s & 0xFFFFFFFF], _np.uint32)


def derive_candidate_seed(seed: int, candidate: int) -> int:
    """Seed for candidate ``i`` of an n>1 fan-out.  Candidate 0 keeps
    the request seed unchanged, so an ``n=1`` rerun of the echoed seed
    replays candidate 0 byte-for-byte."""
    if candidate == 0:
        return int(seed)
    return (int(seed) + 0x9E3779B97F4A7C15 * int(candidate)) % (2 ** 63)


# ---------------------------------------------------------------------------
# traced sampling (called from inside the engine's compiled programs)
# ---------------------------------------------------------------------------

def step_keys(root_keys, indices):
    """Per-slot sampling keys for the tokens at sequence positions
    ``indices``: ``(hi, lo XOR index)``.  The per-draw threefry hash in
    :func:`_sample_row` mixes the key words with the counter, so
    XOR-ing the position into the low word is a full stream split —
    a second ``fold_in`` hash here would buy nothing but an extra
    threefry round compiled into EVERY decode/prefill/verify program
    (measured ~15% of engine warmup).  Broadcasting: ``root_keys``
    (..., 2) uint32 against ``indices`` (...,) int, so the decode step
    (S,), the prefill scalar, and the verify grid (S, Q) all share this
    ONE derivation — bit-identity across paths by construction."""
    import jax.numpy as jnp
    idx = jnp.asarray(indices).astype(jnp.uint32)
    return jnp.stack([jnp.broadcast_to(root_keys[..., 0], idx.shape),
                      root_keys[..., 1] ^ idx], axis=-1)


def _gumbel_row(key, V):
    """Keyed Gumbel noise (V,) from a counter-based integer hash: two
    murmur3 finalizer rounds over (lane, key) — full 32-bit avalanche
    per round, and a pure function of ``(key, lane)`` so every dispatch
    path that derives the same :func:`step_keys` key draws the SAME
    noise.  ``jax.random.uniform`` here would be distributionally
    nicer-pedigreed but compiles a threefry tower into EVERY serving
    program (~1s of engine warmup each, measured); sampling needs an
    unpredictable tie-break, not a cryptographic stream."""
    import jax.numpy as jnp
    x = jnp.arange(V, dtype=jnp.uint32) * jnp.uint32(0x9E3779B9)
    x = x ^ key[1]
    for salt in (key[0], key[1]):
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        x = x ^ (x >> 16) ^ salt
    # top 24 bits → uniform in [2^-24, 1]; the floor keeps log finite
    u = jnp.maximum(x >> 8, 1).astype(jnp.float32) * (2.0 ** -24)
    return -jnp.log(-jnp.log(u))


def _sample_row(lg, temperature, top_k, top_p, bias, key):
    """One slot: biased logits (V,) → sampled token id (scalar int32).
    Branchless — ``temperature == 0`` selects the biased argmax via
    ``where``, so the greedy result is bit-identical to the
    pre-sampling ``jnp.argmax`` while tracing ONE program for every
    parameter setting.  Filter conventions follow
    ``models/gpt.py:_sample_fn``: temperature scales before the
    filters, ``top_k <= 0`` (or >= vocab) disables top-k, and the
    nucleus filter's exclusive cumsum keeps the top-1 token
    unconditionally, so the masked support is never empty."""
    import jax
    import jax.numpy as jnp
    V = lg.shape[-1]
    lgb = (lg + bias).astype(jnp.float32)
    greedy = jnp.argmax(lgb, axis=-1).astype(jnp.int32)
    z = lgb / jnp.maximum(temperature.astype(jnp.float32), 1e-6)
    srt = jnp.sort(z)[::-1]
    kk = jnp.where(top_k <= 0, V, jnp.minimum(top_k, V))
    keep = z >= srt[kk - 1]
    probs = jax.nn.softmax(srt)
    before = jnp.cumsum(probs) - probs        # exclusive: before[0]==0
    cutoff = jnp.min(jnp.where(before < top_p, srt, jnp.inf))
    keep &= z >= cutoff
    sampled = jnp.argmax(jnp.where(keep, z, MASK_OFF)
                         + _gumbel_row(key, V),
                         axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def sample_tokens(logits, temperatures, top_ks, top_ps, biases, keys):
    """Per-slot keyed Gumbel-max sampling: ``logits`` (S, V) →
    token ids (S,) int32.  All parameters are traced operands —
    ``temperatures``/``top_ks``/``top_ps`` (S,), ``biases`` (S, V),
    ``keys`` (S, 2) uint32 from :func:`step_keys`."""
    import jax
    return jax.vmap(_sample_row)(logits, temperatures, top_ks, top_ps,
                                 biases, keys)


def topn_logprobs(logits, biases, n: int):
    """Top-``n`` per-token logprobs of the biased distribution:
    ``(values (..., n) f32, token ids (..., n) int32)``.  ``n`` is
    baked at engine construction (``MXNET_SAMPLING_LOGPROBS_TOPN``) so
    the output arity — and with it the compiled program set — never
    varies per request; per-request N is a host-side slice."""
    import jax
    import jax.numpy as jnp
    lp = jax.nn.log_softmax((logits + biases).astype(jnp.float32),
                            axis=-1)
    vals, ids = jax.lax.top_k(lp, int(n))
    return vals, ids.astype(jnp.int32)


# ---------------------------------------------------------------------------
# host-side planes: stop sequences and constrained output
# ---------------------------------------------------------------------------

def stop_trim(prev_tail, new_tokens, stops):
    """Scan ``new_tokens`` (appended after ``prev_tail``) for the
    first completion of any stop sequence.  Returns ``(kept,
    stopped)``: keep the first ``kept`` new tokens (the stop sequence
    itself stays in the output) and discard the rest — the burst
    over-generation path (``docs/serving.md``; the discarded tail's
    K/V writes were already null-block-redirected in-program)."""
    if not stops:
        return len(new_tokens), False
    window = max(len(s) for s in stops)
    tail = list(prev_tail)[-(window - 1):] if window > 1 else []
    for i, t in enumerate(new_tokens):
        tail.append(int(t))
        for s in stops:
            if len(tail) >= len(s) and tuple(tail[-len(s):]) == tuple(s):
                return i + 1, True
        if len(tail) > window:
            del tail[0]
    return len(new_tokens), False


class JsonMaskMachine:
    """Constrained-output state machine: a character-level pushdown
    automaton over (a useful subset of) the JSON grammar, driving a
    per-slot vocab mask.

    The host advances the machine at each emit boundary with the token
    just emitted; :meth:`mask` renders the set of now-legal next
    tokens as a logit-bias row (0 allowed, :data:`MASK_OFF` not) that
    the engine applies IN-PROGRAM on the next step — the mask is a
    traced operand of the same compiled programs, so constrained
    decoding costs zero extra dispatches.  Because the mask can change
    every token, a constrained slot pins the batcher to the per-step
    decode path (``ContinuousBatcher._burst_ready``): a k-step burst
    could not observe mid-burst mask updates.

    ``token_strs`` maps token id → string; the default serving mapping
    is byte-level (``chr(id)``).  Multi-character tokens are allowed
    when every character advances the automaton.  The grammar requires
    a top-level object or array (the JSON-mode contract), after which
    :attr:`done` flips and the batcher finishes the request."""

    _WS = " \t\n\r"
    _DIGITS = "0123456789"
    # string-interior chars allowed without escaping (printable ASCII
    # minus '"' and '\\'); enough for byte-level serving vocabularies
    _STR_OK = "".join(chr(c) for c in range(0x20, 0x7F)
                      if chr(c) not in '"\\')

    def __init__(self, token_strs):
        self._toks = [str(s) for s in token_strs]
        # state: (mode, stack, literal-remainder); modes are short
        # strings, the stack holds 'O'/'A' container contexts
        self._state = ("value", (), "")

    # -- pure transition core -------------------------------------------
    @classmethod
    def _feed(cls, state, ch):
        """One character; returns the next state or None (illegal)."""
        mode, stack, lit = state
        if mode == "done":
            return None
        if mode == "str" or mode == "str_esc":
            if mode == "str_esc":
                return ("str", stack, "") if ch in '"\\/bfnrt' else None
            if ch == '"':
                return cls._after_value(stack)
            if ch == "\\":
                return ("str_esc", stack, "")
            return ("str", stack, "") if ch in cls._STR_OK else None
        if mode == "lit":
            if lit and ch == lit[0]:
                rest = lit[1:]
                return ("lit", stack, rest) if rest \
                    else cls._after_value(stack)
            return None
        if mode == "num":
            if ch in cls._DIGITS:
                return ("num", stack, "")
            if ch in ".eE+-":        # permissive; parseability is the
                return ("num", stack, "")   # test's oracle, not ours
            # a number is ended by its terminator: close/comma/ws
            nxt = cls._after_value(stack)
            return cls._feed(nxt, ch) if nxt is not None else None
        if mode == "key" or mode == "key_esc":
            if mode == "key_esc":
                return ("key", stack, "") if ch in '"\\/bfnrt' else None
            if ch == '"':
                return ("colon", stack, "")
            if ch == "\\":
                return ("key_esc", stack, "")
            return ("key", stack, "") if ch in cls._STR_OK else None
        if ch in cls._WS:
            return state            # whitespace is legal between tokens
        if mode == "value":
            if ch == "{":
                return ("obj_key0", stack + ("O",), "")
            if ch == "[":
                return ("arr_val0", stack + ("A",), "")
            if not stack:           # top level must be a container
                return None
            if ch == '"':
                return ("str", stack, "")
            if ch in cls._DIGITS or ch == "-":
                return ("num", stack, "")
            if ch == "t":
                return ("lit", stack, "rue")
            if ch == "f":
                return ("lit", stack, "alse")
            if ch == "n":
                return ("lit", stack, "ull")
            return None
        if mode in ("obj_key0", "obj_key"):
            if ch == '"':
                return ("key", stack, "")
            if ch == "}" and mode == "obj_key0":
                return cls._after_value(stack[:-1])
            return None
        if mode == "colon":
            return ("value", stack, "") if ch == ":" else None
        if mode == "arr_val0":
            if ch == "]":
                return cls._after_value(stack[:-1])
            nxt = cls._feed(("value", stack, ""), ch)
            return nxt
        if mode == "obj_next":
            if ch == ",":
                return ("obj_key", stack, "")
            if ch == "}":
                return cls._after_value(stack[:-1])
            return None
        if mode == "arr_next":
            if ch == ",":
                return ("value", stack, "")
            if ch == "]":
                return cls._after_value(stack[:-1])
            return None
        return None

    @staticmethod
    def _after_value(stack):
        if not stack:
            return ("done", (), "")
        return ("obj_next" if stack[-1] == "O" else "arr_next",
                stack, "")

    @classmethod
    def _close_cost(cls, state):
        """Minimal characters from ``state`` to ``done`` — the cost of
        closing every open string/literal/number and container by the
        shortest legal path (a mandatory value costs 1: a digit)."""
        mode, stack, lit = state
        d = len(stack)
        if mode == "done":
            return 0
        if mode == "value":
            return d + (1 if stack else 2)    # top level needs "[]"
        return d + {"num": 0, "str": 1, "str_esc": 2,
                    "lit": len(lit), "key": 3, "key_esc": 4,
                    "colon": 2, "obj_key": 4, "obj_key0": 0,
                    "arr_val0": 0, "obj_next": 0, "arr_next": 0}[mode]

    def _feed_token(self, state, tok: int):
        s = self._toks[tok] if 0 <= int(tok) < len(self._toks) else ""
        if not s:
            return None
        for ch in s:
            state = self._feed(state, ch)
            if state is None:
                return None
        return state

    # -- host API --------------------------------------------------------
    @property
    def done(self) -> bool:
        return self._state[0] == "done"

    def advance(self, tok: int) -> bool:
        """Consume the emitted token; False if it was not legal (the
        in-program mask makes this unreachable on the serving path)."""
        nxt = self._feed_token(self._state, int(tok))
        if nxt is None:
            return False
        self._state = nxt
        return True

    def mask(self, budget: Optional[int] = None) -> _np.ndarray:
        """Logit-bias row for the NEXT token: 0 for every token whose
        whole string advances the automaton, :data:`MASK_OFF`
        otherwise.  O(vocab × token length) host work per emitted
        token — the serving mapping is byte-level, so this is a few
        thousand character transitions at the emit boundary, never on
        the device.

        ``budget`` (tokens still emittable, INCLUDING the one this
        mask gates) additionally drops every token whose resulting
        state could not be closed within what remains — the output is
        then guaranteed to parse before the budget runs out (with
        byte-level tokens, the shortest closing path always survives
        the filter, so the mask can never go empty while
        ``_close_cost(state) <= budget``)."""
        row = _np.full(len(self._toks), MASK_OFF, _np.float32)
        if self.done:
            return row
        for t in range(len(self._toks)):
            nxt = self._feed_token(self._state, t)
            if nxt is None:
                continue
            if budget is not None and self._close_cost(nxt) \
                    > budget - len(self._toks[t]):
                continue
            row[t] = 0.0
        return row
