"""InferenceEngine — donated, jitted forward programs keyed by shape
bucket.

The serving problem on XLA is compile-cache discipline: every distinct
input shape is a fresh trace+compile, so serving raw request shapes
means unbounded compilation.  The engine fixes the shape space up
front — a sorted list of batch-size **buckets** (declared, or
auto-derived powers of two up to ``max_batch_size``) — and pads every
request batch up to the next bucket, so a stream of mixed-size requests
leaves the jit cache bounded by the bucket count (the acceptance
invariant: exactly one compiled program per (model, bucket)).

One engine wraps one model — a Gluon ``(Hybrid)Block``
(:meth:`from_block`), a bound ``Module`` (:meth:`from_module`), or an
exported/checkpointed symbol+params pair (:meth:`from_symbol`,
:meth:`from_export`) — as a single pure function
``(inputs, params, aux, key) -> outputs`` under ``jax.jit`` with the
input batch donated (the request buffers are dead after dispatch, so
XLA may reuse them for outputs).  Parameter values are fetched per
dispatch, so live weight updates (e.g. a trainer running in the same
process) propagate without recompiling.

The jit is wrapped in :func:`telemetry.instrument_jit` under
``serving:<name>`` — compile cache hits/misses, cost analysis, and
``jit:serving:<name>`` spans ride the existing observability plane.
"""
from __future__ import annotations

import warnings
import weakref
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray
from .. import telemetry as _telemetry
from .. import telemetry_device as _telemetry_device
from .. import health as _health

__all__ = ["InferenceEngine", "GenerationEngine", "derive_buckets",
           "derive_prefill_buckets", "ensure_compile_cache"]


_compile_cache_dir: Optional[str] = None


def ensure_compile_cache() -> Optional[str]:
    """Point JAX's persistent compilation cache at
    ``MXNET_COMPILE_CACHE_DIR`` (idempotent; returns the active dir or
    None when the env var is unset).

    Every engine constructor calls this BEFORE building its jitted
    programs, so a fresh replica's ``warmup()`` loads compiled
    executables from disk instead of re-tracing through XLA — the
    instant-start half of the serve-fleet story (docs/serving.md):
    replica N pays the compile once, replicas N+1.. hit the shared
    directory.  The entry-size/compile-time floors are dropped to zero
    because serving programs are many small programs (one per bucket)
    — exactly the population the default floors would skip."""
    global _compile_cache_dir
    from ..base import getenv
    cache_dir = getenv("MXNET_COMPILE_CACHE_DIR")
    if not cache_dir or _compile_cache_dir is not None:
        # Configure-once: jax's compilation cache dir cannot be safely
        # re-pointed mid-process, so later engine inits (even with a
        # changed env) keep the first wiring.
        return _compile_cache_dir
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax snapshots the cache at the FIRST compile; if anything
        # compiled before we got here (eager param init, a warmup
        # forward) the cache latched "disabled" — reset so the next
        # compile re-initializes against the dir we just set.
        from jax._src import compilation_cache as _jax_cc
        _jax_cc.reset_cache()
    except Exception as e:      # an old jax without the knobs serves
        warnings.warn(          # fine, just without instant starts
            f"MXNET_COMPILE_CACHE_DIR ignored: {e}")
        return _compile_cache_dir
    _compile_cache_dir = str(cache_dir)
    return _compile_cache_dir


def derive_buckets(max_batch_size: int) -> Tuple[int, ...]:
    """Powers of two up to (and always including) ``max_batch_size``:
    ``derive_buckets(32) == (1, 2, 4, 8, 16, 32)``,
    ``derive_buckets(24) == (1, 2, 4, 8, 16, 24)``."""
    m = int(max_batch_size)
    if m < 1:
        raise MXNetError(f"max_batch_size must be >= 1, got {m}")
    out, b = [], 1
    while b < m:
        out.append(b)
        b *= 2
    out.append(m)
    return tuple(out)


def _canon_specs(input_specs):
    """[(per-example shape, dtype)] with the batch dim EXCLUDED."""
    if input_specs is None:
        return None
    out = []
    for spec in input_specs:
        if isinstance(spec, tuple) and len(spec) == 2 \
                and isinstance(spec[0], (tuple, list)):
            shape, dtype = spec
        else:
            shape, dtype = spec, _np.float32
        out.append((tuple(int(d) for d in shape), _np.dtype(dtype)))
    return out


def _register_device_observers(engine) -> None:
    """Enroll an engine in the device-observability plane
    (telemetry_device): a program-inventory callback (``GET /programs``,
    flight dumps) and per-owner memory attribution (params, and the KV
    cache for generation engines).  All weak — a telemetry registration
    must never keep a dead engine's caches alive; a collected engine
    reports empty/zero until a successor with the same name replaces
    the registration."""
    wref = weakref.ref(engine)

    def inventory():
        eng = wref()
        return eng.program_inventory() if eng is not None else {}

    def param_bytes():
        eng = wref()
        if eng is None:
            return 0
        try:
            pv, av = eng._param_fn()
            return sum(int(v.size) * v.dtype.itemsize
                       for vals in (pv, av) for v in vals)
        except Exception:
            return 0

    _telemetry_device.register_inventory(engine.name, inventory)
    _telemetry_device.register_owner("params:" + engine.name, param_bytes)
    if hasattr(engine, "cache_bytes"):
        def kv_bytes():
            eng = wref()
            return eng.cache_bytes if eng is not None else 0
        _telemetry_device.register_owner("kv:" + engine.name, kv_bytes)


class InferenceEngine:
    """A model as a bucketed set of compiled inference programs.

    ``pure_fn(in_vals, param_vals, aux_vals, key) -> tuple(outputs)``
    must be a pure jax function; ``param_fn() -> (param_vals, aux_vals)``
    supplies the CURRENT weight values per dispatch.  Most callers build
    engines via :meth:`from_block` / :meth:`from_symbol` /
    :meth:`from_module` / :meth:`from_export` instead of this
    constructor.
    """

    def __init__(self, pure_fn: Callable, input_names: Sequence[str],
                 param_fn: Callable, *, name: str = "model",
                 buckets: Optional[Sequence[int]] = None,
                 max_batch_size: Optional[int] = None,
                 input_specs=None, ctx=None):
        import jax
        ensure_compile_cache()
        self.name = str(name)
        self.input_names = [str(n) for n in input_names]
        self._param_fn = param_fn
        self._ctx = ctx if ctx is not None else current_context()
        self.input_specs = _canon_specs(input_specs)
        if buckets:
            self.buckets = tuple(sorted({int(b) for b in buckets}))
            if self.buckets[0] < 1:
                raise MXNetError(f"buckets must be >= 1: {self.buckets}")
        elif max_batch_size:
            self.buckets = derive_buckets(max_batch_size)
        else:
            self.buckets = ()       # exact-shape mode (the predict ABI)
        self.max_batch_size = self.buckets[-1] if self.buckets else None
        self._jit = jax.jit(pure_fn, donate_argnums=(0,))
        self._call = _telemetry.instrument_jit("serving:" + self.name,
                                               self._jit)
        self._shapes_seen = set()
        self._warmup_done = False
        _register_device_observers(self)

    @property
    def input_dtypes(self):
        """Declared per-input dtypes (from ``input_specs``), or None
        when the engine was built without specs — the HTTP front-end
        uses these to decode JSON tensors at the model's real dtypes
        instead of forcing float32."""
        if not self.input_specs:
            return None
        return [dtype for _, dtype in self.input_specs]

    @property
    def warm(self) -> bool:
        """True once every declared bucket has a compiled program (the
        readiness gate: a replica is not *ready* until its programs
        are).  Bucket-free (exact-shape) engines are vacuously warm."""
        if not self.buckets:
            return True
        if self._warmup_done:
            return True
        return self.compiled_programs() >= len(self.buckets)

    # -- shape bucketing ------------------------------------------------
    def bucket_for(self, n: int) -> Optional[int]:
        """Smallest bucket that fits ``n`` rows (None when ``n`` exceeds
        the largest bucket — the caller chunks)."""
        for b in self.buckets:
            if b >= int(n):
                return b
        return None

    # -- dispatch -------------------------------------------------------
    def _prepare(self, arrays, target: Optional[int]):
        """Convert to jax values, pad the batch dim up to ``target``.
        Buffers we did not create are copied — the jit donates its input
        batch, and donation must never eat a caller-owned array."""
        import jax.numpy as jnp
        vals = []
        for a in arrays:
            if isinstance(a, NDArray):
                v, owned = a._data, False
            elif isinstance(a, jnp.ndarray) and not isinstance(a, _np.ndarray):
                v, owned = a, False
            else:
                v, owned = jnp.asarray(a), True
            if target is not None and v.shape[0] != target:
                pad = target - int(v.shape[0])
                if pad < 0:
                    raise MXNetError(
                        f"{self.name}: batch {v.shape[0]} exceeds bucket "
                        f"{target}")
                v = jnp.concatenate(
                    [v, jnp.zeros((pad,) + tuple(v.shape[1:]), v.dtype)],
                    axis=0)
            elif not owned:
                v = v.copy()
            vals.append(v)
        return tuple(vals)

    def _dispatch(self, in_vals: tuple):
        from .. import random as _random
        self._shapes_seen.add(tuple(v.shape for v in in_vals))
        param_vals, aux_vals = self._param_fn()
        key = _random.new_key(self._ctx)
        try:
            with _telemetry.trace_span("serve.infer", cat="serving",
                                       model=self.name,
                                       batch=int(in_vals[0].shape[0])):
                # donation is advisory on CPU; silence the per-call notice
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable")
                    return self._call(in_vals, tuple(param_vals),
                                      tuple(aux_vals), key)
        except Exception as e:
            if _telemetry_device.is_oom(e):
                _telemetry_device.report_oom("serving." + self.name, e,
                                             model=self.name)
            raise

    def predict(self, arrays: Sequence) -> List:
        """Run one batch: pad up to the next bucket, dispatch ONE
        compiled program, slice outputs back to the true row count.
        Batches larger than the biggest bucket are chunked.  Outputs are
        jax arrays (``np.asarray`` them for host use)."""
        arrays = list(arrays)
        if len(arrays) != len(self.input_names):
            raise MXNetError(
                f"{self.name}: got {len(arrays)} inputs, expected "
                f"{len(self.input_names)} ({self.input_names})")
        if not self.buckets:
            return list(self._dispatch(self._prepare(arrays, None)))
        n = int(arrays[0].shape[0])
        bucket = self.bucket_for(n)
        if bucket is None:          # chunk by the largest bucket
            import jax.numpy as jnp
            step = self.buckets[-1]
            chunks = [self.predict([a[i:i + step] for a in arrays])
                      for i in range(0, n, step)]
            return [jnp.concatenate([c[k] for c in chunks], axis=0)
                    for k in range(len(chunks[0]))]
        outs = self._dispatch(self._prepare(arrays, bucket))
        if bucket == n:
            return list(outs)
        return [o[:n] for o in outs]

    def run_exact(self, arrays: Sequence) -> List:
        """Dispatch at the exact input shapes, no bucketing — the
        per-shape compiled-program cache for the C predict ABI, where
        shapes are declared up front and ``reshape`` handles share one
        engine."""
        return list(self._dispatch(self._prepare(list(arrays), None)))

    def warmup(self) -> int:
        """AOT-compile every declared bucket (requires ``input_specs``);
        returns the number of buckets warmed."""
        if not self.buckets:
            return 0
        if not self.input_specs:
            raise MXNetError(
                f"{self.name}: warmup needs input_specs (per-example "
                "shapes) to synthesize bucket batches")
        for b in self.buckets:
            self.predict([_np.zeros((b,) + shape, dtype)
                          for shape, dtype in self.input_specs])
        self._warmup_done = True
        return len(self.buckets)

    def compiled_programs(self) -> int:
        """Entries in the jit compile cache — bounded by the bucket
        count for bucketed serving."""
        try:
            return int(self._jit._cache_size())
        except Exception:
            return len(self._shapes_seen)

    def program_inventory(self) -> dict:
        """Runtime program-set inventory (``GET /programs``, flight
        dumps): expected vs compiled program counts plus this engine's
        dispatch-ledger row (dispatch count, wall-time stats,
        last-dispatch age)."""
        site = "serving:" + self.name
        ledger = _telemetry.dispatch_ledger(prefix=site)
        return {
            "model": self.name,
            "expected_programs": len(self.buckets) or None,
            "compiled_programs": self.compiled_programs(),
            "warm": self.warm,
            "programs": {k: v for k, v in ledger.items() if k == site},
        }

    def __repr__(self):
        return (f"<InferenceEngine {self.name!r}: inputs="
                f"{self.input_names}, buckets={list(self.buckets)}, "
                f"programs={self.compiled_programs()}>")

    # -- constructors ---------------------------------------------------
    @classmethod
    def from_block(cls, block, input_specs, *, name: Optional[str] = None,
                   buckets=None, max_batch_size: Optional[int] = None,
                   ctx=None):
        """Wrap a Gluon ``Block``/``HybridBlock``.  ``input_specs`` are
        per-example shapes (batch dim excluded), e.g. ``[(784,)]``;
        deferred-init parameters are settled with one zero forward."""
        from .. import ndarray as nd
        from .. import autograd as _ag
        from ..gluon.block import functional_call
        specs = _canon_specs(input_specs)
        if not specs:
            raise MXNetError("from_block: input_specs is required")
        ctx = ctx if ctx is not None else current_context()
        params = list(block.collect_params().values())
        if any(p._deferred_init is not None or p._data is None
               for p in params):
            probe = [nd.zeros((1,) + shape, ctx=ctx, dtype=dtype)
                     for shape, dtype in specs]
            with _ag.pause(train_mode=False):
                block(*probe)
            params = list(block.collect_params().values())
        trainable = [p for p in params if p.grad_req != "null"]
        aux = [p for p in params if p.grad_req == "null"]

        def param_fn():
            return (tuple(p._data._data for p in trainable),
                    tuple(p._data._data for p in aux))

        def pure(in_vals, param_vals, aux_vals, key):
            inputs_nd = [NDArray(v) for v in in_vals]
            out_vals, _ = functional_call(
                block, trainable, list(param_vals), aux, list(aux_vals),
                inputs_nd, False, key)
            return tuple(out_vals)

        names = ["data"] if len(specs) == 1 else \
            [f"data{i}" for i in range(len(specs))]
        return cls(pure, names, param_fn,
                   name=name or getattr(block, "name", "block"),
                   buckets=buckets, max_batch_size=max_batch_size or 32,
                   input_specs=specs, ctx=ctx)

    @classmethod
    def from_symbol(cls, symbol, arg_params, aux_params, input_names,
                    *, input_specs=None, output_names=(),
                    name: Optional[str] = None, buckets=None,
                    max_batch_size: Optional[int] = None, ctx=None):
        """Wrap a symbol + decoded params (a checkpoint / export pair).
        ``output_names`` selects internal outputs by name (the partial-out
        contract of the predict ABI); empty means the symbol's own
        outputs.  Without ``buckets``/``max_batch_size`` the engine runs
        in exact-shape mode (:meth:`run_exact`)."""
        from .. import ndarray as nd
        from .. import autograd as _ag
        from .. import random as _random
        from ..symbol import symbol as sym_mod
        from ..symbol.symbol import eval_graph
        if output_names:
            internals = symbol.get_internals()
            symbol = sym_mod.Group([internals[str(n)]
                                    for n in output_names])
        input_names = [str(n) for n in input_names]
        ctx = ctx if ctx is not None else current_context()
        arg_params = arg_params or {}
        aux_params = aux_params or {}
        param_names = [n for n in symbol.list_arguments()
                       if n not in input_names]
        for n in param_names:
            if n not in arg_params:
                raise ValueError(f"parameter {n!r} missing from the "
                                 ".params bytes and not a declared input")
        aux_names = symbol.list_auxiliary_states()
        for n in aux_names:
            if n not in aux_params:
                raise MXNetError(f"from_symbol: aux_states missing {n!r}")
        as_nd = lambda v: v if isinstance(v, NDArray) \
            else nd.array(v, ctx=ctx)
        params = {n: as_nd(arg_params[n]) for n in param_names}
        aux = {n: as_nd(aux_params[n]) for n in aux_names}

        def param_fn():
            return (tuple(params[n]._data for n in param_names),
                    tuple(aux[n]._data for n in aux_names))

        def pure(in_vals, param_vals, aux_vals, key):
            values = {n: NDArray(v) for n, v in zip(input_names, in_vals)}
            values.update({n: NDArray(v)
                           for n, v in zip(param_names, param_vals)})
            values.update({n: NDArray(v)
                           for n, v in zip(aux_names, aux_vals)})
            sink = {}
            with _ag.pause(train_mode=False), _random.trace_stream(key):
                outs = eval_graph(symbol, values, False, sink)
            return tuple(o._data for o in outs)

        return cls(pure, input_names, param_fn,
                   name=name or getattr(symbol, "name", "symbol"),
                   buckets=buckets, max_batch_size=max_batch_size,
                   input_specs=input_specs, ctx=ctx)

    @classmethod
    def from_module(cls, module, **kw):
        """Wrap a bound, initialized ``Module``.  Data names become the
        engine inputs; label arguments (if the symbol has any) ride as
        fixed arrays from the module's executor — suitable for
        label-free inference outputs."""
        if not module.binded or not module.params_initialized:
            raise MXNetError("from_module: bind() and init_params() first")
        input_names = list(module._data_names)
        arg = dict(module._exec.arg_dict)
        params = {n: v for n, v in arg.items() if n not in input_names}
        kw.setdefault("input_specs",
                      [(tuple(d.shape[1:]), d.dtype)
                       for d in module._data_shapes])
        kw.setdefault("max_batch_size",
                      int(module._data_shapes[0].shape[0])
                      if module._data_shapes else None)
        kw.setdefault("name", getattr(module._symbol, "name", "module"))
        return cls.from_symbol(module._symbol, params,
                               dict(module._exec.aux_dict), input_names,
                               **kw)

    @classmethod
    def from_export(cls, prefix: str, epoch: int = 0,
                    input_names=("data",), **kw):
        """Load a ``HybridBlock.export`` / ``model.save_checkpoint``
        artifact pair (``<prefix>-symbol.json`` +
        ``<prefix>-NNNN.params``)."""
        import os
        from .. import model
        sym, arg_params, aux_params = model.load_checkpoint(prefix,
                                                            int(epoch))
        kw.setdefault("name", os.path.basename(str(prefix)) or "export")
        return cls.from_symbol(sym, arg_params, aux_params, input_names,
                               **kw)


# ===========================================================================
# GenerationEngine — continuous-batching autoregressive decode
# ===========================================================================

def derive_prefill_buckets(max_len: int, smallest: int = 8):
    """Prompt-length buckets: powers of two from ``smallest`` up to (and
    always including) ``max_len`` — ``derive_prefill_buckets(128) ==
    (8, 16, 32, 64, 128)``.  One compiled prefill program per bucket."""
    m = int(max_len)
    if m < 1:
        raise MXNetError(f"max_len must be >= 1, got {m}")
    out, b = [], min(int(smallest), m)
    while b < m:
        out.append(b)
        b *= 2
    out.append(m)
    return tuple(out)


class GenerationEngine:
    """Autoregressive generation as a closed set of compiled programs
    over a PREALLOCATED per-layer KV cache ``[slots, heads, max_len,
    head_dim]``.

    The naive serving path re-runs prefill over the whole growing
    context every token — O(n^2) work and one fresh dispatch per request
    per token.  This engine splits the work once:

    * ``prefill(tokens, slot)`` — full-prefix forward at the request's
      prompt-length bucket, writing the slot's K/V rows and returning
      the first generated token.  One compiled program per prefill
      bucket (:func:`derive_prefill_buckets`).
    * ``decode(last_tokens, positions)`` — ONE fixed-shape dispatch
      advancing every slot one token: embeds each slot's last token at
      its own position, appends K/V at that position, and attends over
      its live prefix via :func:`kernels.flash_attention.decode_attention`.
      Exactly one compiled program, regardless of how many requests are
      in flight or how long they run.

    Both programs take the whole cache DONATED (the engine owns it and
    rebinds the returned buffers), so XLA updates the cache in place.
    The cache is single-writer by contract: only the continuous
    batcher's worker thread dispatches.  Free slots still flow through
    ``decode`` (their writes land in their own rows at position 0 and
    are overwritten by the next prefill), which is what keeps the
    program count at one.

    Decoding is greedy (argmax) — the serving contract is determinism:
    cached decode must match the full re-forward token-for-token.

    **Paged mode** (default; ``MXNET_KV_PAGED=0`` falls back to the dense
    layout above): the cache becomes per-layer block pools ``[num_blocks,
    heads, block_size, head_dim]`` managed by a
    :class:`~.kvcache.BlockPool`, and each slot addresses its K/V through
    an int32 *block table* operand — an (S, max_blocks) array that enters
    the SAME compiled programs as data, never as a shape.  A request
    reserves only ``ceil((prompt + budget) / block_size)`` blocks, so the
    same byte budget admits many more concurrent streams, and full prompt
    blocks are shared across requests via the pool's prefix cache (a
    prefix hit prefills only the unshared suffix).  The program set stays
    closed: one miss-prefill per bucket, one suffix-prefill per bucket
    (prefix hits), and ONE paged decode.  Decode attention routes through
    :func:`kernels.flash_attention.paged_decode_attention`, whose lax
    gather reference keeps paged decode bit-identical to dense.
    """

    def __init__(self, block, *, name: Optional[str] = None,
                 max_slots: Optional[int] = None,
                 max_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 paged: Optional[bool] = None,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 scan_steps: Optional[int] = None,
                 logprobs_topn: Optional[int] = None,
                 ctx=None):
        import jax
        from ..base import getenv_int, getenv_bool
        ensure_compile_cache()
        for attr in ("embed", "pos_embed", "cells", "ln_f", "_units",
                     "_max_length"):
            if not hasattr(block, attr):
                raise MXNetError(
                    "GenerationEngine needs a GPT-style block (embed/"
                    f"pos_embed/cells/ln_f); {type(block).__name__} has "
                    f"no {attr!r}")
        self.block = block
        self.name = str(name or getattr(block, "name", "gpt"))
        self._ctx = ctx if ctx is not None else current_context()
        self.max_slots = int(max_slots
                             or getenv_int("MXNET_GEN_MAX_SLOTS", 8))
        if self.max_slots < 1:
            raise MXNetError(f"max_slots must be >= 1: {self.max_slots}")
        blk_len = int(block._max_length)
        self.max_len = min(int(max_len
                               or getenv_int("MXNET_GEN_MAX_LEN", blk_len)),
                           blk_len)
        if self.max_len < 2:
            raise MXNetError(f"max_len must be >= 2: {self.max_len}")
        self._cells = list(block.cells._children.values())
        self.num_layers = len(self._cells)
        at = self._cells[0].attention
        self.num_heads = int(at._num_heads)
        self.head_dim = int(block._units) // self.num_heads
        if prefill_buckets:
            self.prefill_buckets = tuple(sorted(
                {int(b) for b in prefill_buckets}))
            if self.prefill_buckets[0] < 1 \
                    or self.prefill_buckets[-1] > self.max_len:
                raise MXNetError(
                    f"prefill buckets must be in [1, {self.max_len}]: "
                    f"{self.prefill_buckets}")
        else:
            self.prefill_buckets = derive_prefill_buckets(self.max_len)
        # paged KV cache (serving/kvcache.py): on by default, dense stays
        # available as the fallback and parity oracle
        self.paged = bool(getenv_bool("MXNET_KV_PAGED", True)
                          if paged is None else paged)
        self.block_size = int(block_size
                              or getenv_int("MXNET_KV_BLOCK_SIZE", 16))
        if self.block_size < 1:
            raise MXNetError(f"block_size must be >= 1: {self.block_size}")
        self.prefix_cache_enabled = self.paged and bool(
            getenv_bool("MXNET_KV_PREFIX_CACHE", True)
            if prefix_cache is None else prefix_cache)
        if self.paged:
            from .kvcache import BlockPool
            self.max_blocks_per_slot = -(-self.max_len // self.block_size)
            nb = int(num_blocks or getenv_int("MXNET_KV_NUM_BLOCKS", 0)) \
                or 1 + self.max_slots * self.max_blocks_per_slot
            if nb < 1 + self.max_blocks_per_slot:
                raise MXNetError(
                    f"num_blocks {nb} cannot hold even one max_len slot "
                    f"({self.max_blocks_per_slot} blocks + null block)")
            self.num_blocks = nb
            self.pool = BlockPool(nb, self.block_size,
                                  prefix_cache=self.prefix_cache_enabled,
                                  model=self.name)
        else:
            self.max_blocks_per_slot = 0
            self.num_blocks = 0
            self.pool = None
        self._warming = False
        # multi-token decode bursts (docs/serving.md): lax.scan
        # ``scan_steps`` decode steps into ONE dispatch with in-program
        # termination.  0 disables the burst program entirely; the value
        # is baked into the trace at first dispatch, so it must be set
        # (ctor / attach_draft) BEFORE warmup.
        self.scan_steps = int(scan_steps if scan_steps is not None
                              else getenv_int("MXNET_DECODE_SCAN_STEPS",
                                              8))
        if self.scan_steps < 0:
            raise MXNetError(
                f"scan_steps must be >= 0: {self.scan_steps}")
        # health plane (health.py): captured at construction so the jit
        # cache never mixes output arities — flipping MXNET_HEALTH_PLANE
        # mid-process takes effect on the next engine, not this one
        self._health_on = _health.enabled()
        self._last_decode_health = None
        self._settle_params()
        # sampling plane (serving/sampling.py, docs/serving.md
        # "Sampling"): per-slot temperature / top-k / top-p / bias row /
        # RNG root key are TRACED OPERANDS of the same compiled
        # programs — the defaults (temperature 0, zero bias) reproduce
        # the pre-sampling greedy argmax bit-for-bit, and flipping any
        # of them never recompiles.  The logprobs top-N is baked at
        # construction like the health plane: it changes every
        # program's output arity, so it must never vary per request
        # (per-request N is a host-side slice up to this cap).
        vs = getattr(block, "_vocab_size", None)
        self.vocab_size = int(vs if vs is not None
                              else self.block.embed.weight.shape[0])
        self.logprobs_topn = max(0, min(
            int(logprobs_topn if logprobs_topn is not None
                else getenv_int("MXNET_SAMPLING_LOGPROBS_TOPN", 5)),
            self.vocab_size))
        self._samp_temp = _np.zeros(self.max_slots, _np.float32)
        self._samp_topk = _np.zeros(self.max_slots, _np.int32)
        self._samp_topp = _np.ones(self.max_slots, _np.float32)
        self._samp_bias = _np.zeros((self.max_slots, self.vocab_size),
                                    _np.float32)
        self._samp_keys = _np.zeros((self.max_slots, 2), _np.uint32)
        self._samp_dev = None
        self._last_logprobs = None
        self._last_prefill_logprobs = None
        self._last_verify_logprobs = None
        if self.paged:
            self._prefill_jit = jax.jit(self._prefill_paged_pure,
                                        donate_argnums=(0,))
            self._prefill_ext_jit = jax.jit(self._prefill_ext_pure,
                                            donate_argnums=(0,))
            self._prefill_ext = _telemetry.instrument_jit(
                "serving:" + self.name + ":prefill_ext",
                self._prefill_ext_jit)
            self._decode_jit = jax.jit(self._decode_paged_pure,
                                       donate_argnums=(0,))
            self._decode_burst_jit = jax.jit(self._decode_burst_paged_pure,
                                             donate_argnums=(0,))
            self._verify_jit = jax.jit(self._verify_paged_pure,
                                       donate_argnums=(0,))
        else:
            self._prefill_jit = jax.jit(self._prefill_pure,
                                        donate_argnums=(0,))
            self._prefill_ext_jit = None
            self._prefill_ext = None
            self._decode_jit = jax.jit(self._decode_pure,
                                       donate_argnums=(0,))
            self._decode_burst_jit = jax.jit(self._decode_burst_pure,
                                             donate_argnums=(0,))
            self._verify_jit = jax.jit(self._verify_pure,
                                       donate_argnums=(0,))
        self._prefill = _telemetry.instrument_jit(
            "serving:" + self.name + ":prefill", self._prefill_jit)
        self._decode = _telemetry.instrument_jit(
            "serving:" + self.name + ":decode", self._decode_jit)
        self._decode_burst = _telemetry.instrument_jit(
            "serving:" + self.name + ":decode_burst",
            self._decode_burst_jit)
        self._verify = _telemetry.instrument_jit(
            "serving:" + self.name + ":verify", self._verify_jit)
        # speculative decoding: a draft engine attached via attach_draft
        # proposes spec_k tokens per slot; THE verify program scores all
        # spec_k + 1 positions in one dispatch (exactly one extra
        # compiled program — Q is baked from spec_k, never per-request)
        self.draft: Optional["GenerationEngine"] = None
        self.spec_k = 0
        self._warmup_done = False
        self.reset()
        _register_device_observers(self)

    # -- parameters -----------------------------------------------------
    def _settle_params(self):
        from .. import ndarray as nd
        from .. import autograd as _ag
        params = list(self.block.collect_params().values())
        if any(p._deferred_init is not None or p._data is None
               for p in params):
            probe = nd.array(_np.zeros((1, 2), _np.int32), ctx=self._ctx)
            with _ag.pause(train_mode=False):
                self.block(probe)
            params = list(self.block.collect_params().values())
        self._trainable = [p for p in params if p.grad_req != "null"]
        self._aux = [p for p in params if p.grad_req == "null"]

    def _param_fn(self):
        return (tuple(p._data._data for p in self._trainable),
                tuple(p._data._data for p in self._aux))

    def _with_params(self, param_vals, aux_vals, key, body):
        """functional_call's substitution mechanics with a custom body:
        swap jax values/tracers into the Parameters, run ``body`` in
        inference mode under the traced RNG stream, restore."""
        from .. import autograd as _ag
        from .. import random as _random
        all_params = self._trainable + self._aux
        all_vals = list(param_vals) + list(aux_vals)
        saved = [p._data._data for p in all_params]
        try:
            for p, v in zip(all_params, all_vals):
                p._data._set_data(v)
            with _ag.pause(train_mode=False), _random.trace_stream(key):
                return body()
        finally:
            for p, v in zip(all_params, saved):
                p._data._set_data(v)

    # -- sampling plane --------------------------------------------------
    # Host side: per-slot numpy arrays mirrored to ONE cached device
    # tuple (like _tables_dev), invalidated on any slot update.  Traced
    # side: the token at sequence position t is sampled with
    # ``step_keys(root, t)`` — the key depends only on (root, position),
    # never on which program produced the logits, which is what makes
    # seeded runs bit-identical across per-step decode, scanned bursts,
    # and speculative verify (the Gumbel-coupled acceptance argument in
    # :meth:`spec_step`).

    def set_slot_sampling(self, slot: int, params=None) -> None:
        """Install a request's sampling parameters into ``slot`` before
        its prefill (``params`` None → greedy defaults).  Cascades to an
        attached draft engine so draft proposals are drawn from the SAME
        key stream — the coupling that stochastic speculation needs.
        Slots are NOT auto-cleared on release: prefill() itself releases
        a stale slot, so clearing there would clobber parameters set
        just before admission.  Every join sets its slot explicitly."""
        from .sampling import SamplingParams, root_key
        s = int(slot)
        if not 0 <= s < self.max_slots:
            raise MXNetError(f"{self.name}: slot {s} out of range")
        p = params if params is not None else SamplingParams()
        self._samp_temp[s] = float(p.temperature)
        self._samp_topk[s] = int(p.top_k)
        self._samp_topp[s] = float(p.top_p)
        row = _np.zeros(self.vocab_size, _np.float32)
        if p.logit_bias:
            for t, b in p.logit_bias.items():
                if 0 <= int(t) < self.vocab_size:
                    row[int(t)] = float(b)
        self._samp_bias[s] = row
        self._samp_keys[s] = root_key(p.seed or 0)
        self._samp_dev = None
        if self.draft is not None:
            self.draft.set_slot_sampling(slot, params)

    def update_slot_bias(self, slot: int, row) -> None:
        """Replace ``slot``'s logit-bias row (constrained-output plane:
        the batcher composes the request's static logit_bias with the
        grammar machine's mask at each emit boundary; the new row is a
        traced operand of the NEXT dispatch).  Cascades to the draft so
        constrained slots never propose illegal tokens."""
        s = int(slot)
        self._samp_bias[s] = _np.asarray(row, _np.float32).reshape(
            self.vocab_size)
        self._samp_dev = None
        if self.draft is not None:
            self.draft.update_slot_bias(slot, row)

    def last_logprobs(self):
        """Device arrays from the most recent decode/burst dispatch when
        ``logprobs_topn > 0``: ``(values, token ids)`` shaped (S, N) for
        per-step decode or (k, S, N) for a burst; None when disabled.
        Like :meth:`last_decode_health`, the token read already synced
        the dispatch, so pulling these costs no extra round-trip."""
        return self._last_logprobs

    def last_prefill_logprobs(self):
        """``(values, ids)`` each shaped (N,) for the most recent
        prefill's first sampled token; None when disabled."""
        return self._last_prefill_logprobs

    def last_verify_logprobs(self):
        """``(values, ids)`` each shaped (S, Q, N) for the most recent
        verify dispatch; None when disabled."""
        return self._last_verify_logprobs

    def _samp_tuple(self):
        """The (S,)-wide sampling operand tuple, device-cached."""
        import jax.numpy as jnp
        if self._samp_dev is None:
            self._samp_dev = (jnp.asarray(self._samp_temp),
                              jnp.asarray(self._samp_topk),
                              jnp.asarray(self._samp_topp),
                              jnp.asarray(self._samp_bias),
                              jnp.asarray(self._samp_keys))
        return self._samp_dev

    def _slot_samp(self, slot: int):
        """Per-slot scalar sampling operands for the prefill programs
        (temp (), top_k (), top_p (), bias (V,), root (2,))."""
        import jax.numpy as jnp
        s = int(slot)
        return (jnp.asarray(self._samp_temp[s]),
                jnp.asarray(self._samp_topk[s]),
                jnp.asarray(self._samp_topp[s]),
                jnp.asarray(self._samp_bias[s]),
                jnp.asarray(self._samp_keys[s]))

    # traced helpers (called from inside the pure programs)
    def _sample_prefill(self, last, first_pos, samp):
        """First generated token from prefill logits ``last`` (V,);
        ``first_pos`` is the sequence position it will occupy."""
        from .sampling import _sample_row, step_keys, topn_logprobs
        temp, topk, topp, bias, root = samp
        skey = step_keys(root, first_pos)
        first = _sample_row(last, temp, topk, topp, bias, skey)
        lp = topn_logprobs(last, bias, self.logprobs_topn) \
            if self.logprobs_topn else None
        return first, lp

    def _sample_step(self, lg, key_idx, samp):
        """Next token per slot from decode logits ``lg`` (S, V);
        ``key_idx`` (S,) the sequence positions the sampled tokens will
        occupy (write-head + 1 — the burst scan's position carry feeds
        this per step, which IS the in-program key split)."""
        from .sampling import step_keys, sample_tokens
        temps, topks, topps, biases, roots = samp
        return sample_tokens(lg, temps, topks, topps, biases,
                             step_keys(roots, key_idx))

    def _sample_verify(self, logits, pos_q, samp):
        """Per-position sampled tokens for the verify program: logits
        (S, Q, V), ``pos_q`` (S, Q) the positions of the consumed
        tokens; output (S, Q) — column j is the token AFTER consuming
        position pos_q[:, j], keyed at pos_q + 1, so each column is
        bit-identical to what per-step decode would sample there."""
        import jax
        from .sampling import _sample_row, step_keys
        temps, topks, topps, biases, roots = samp
        keys = step_keys(roots[:, None, :], pos_q + 1)
        row = jax.vmap(_sample_row, in_axes=(0, None, None, None,
                                             None, 0))
        return jax.vmap(row)(logits, temps, topks, topps, biases, keys)

    # -- pure programs --------------------------------------------------
    def _prefill_pure(self, cache, tokens, n_valid, slot, samp,
                      param_vals, aux_vals, key):
        """tokens (1, Tb) int32 (zero-padded past ``n_valid``), scalar
        ``slot``: run the full-prefix forward (causal, so the first
        ``n_valid`` positions are exact regardless of padding), write the
        slot's K/V rows for positions [0, Tb), return (cache', first
        generated token)."""
        import jax.numpy as jnp
        from jax import lax
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        Tb = tokens.shape[1]

        def body():
            x = self.block._embed_at(NDArray(tokens))
            ks, vs = [], []
            for cell in self._cells:
                x, k, v = cell.prime(x)
                ks.append(k._data)
                vs.append(v._data)
            logits = self.block._project(self.block.ln_f(x))
            return logits._data, ks, vs

        logits, ks, vs = self._with_params(param_vals, aux_vals, key, body)
        out = list(cache)
        for l in range(L):
            kh = ks[l].reshape(Tb, H, D).transpose(1, 0, 2)[None]
            vh = vs[l].reshape(Tb, H, D).transpose(1, 0, 2)[None]
            out[l] = lax.dynamic_update_slice(
                out[l], kh.astype(out[l].dtype), (slot, 0, 0, 0))
            out[L + l] = lax.dynamic_update_slice(
                out[L + l], vh.astype(out[L + l].dtype), (slot, 0, 0, 0))
        last = jnp.take(logits[0], n_valid - 1, axis=0)
        first, lp = self._sample_prefill(last, n_valid, samp)
        if lp is not None:
            return tuple(out), first, lp
        return tuple(out), first

    def _decode_pure(self, cache, last_tokens, positions, samp,
                     param_vals, aux_vals, key):
        """One token for EVERY slot: last_tokens (S, 1) int32, positions
        (S,) int32 (the index each slot writes this step).  Free slots
        ride along writing into their own row at position 0 — harmless,
        the next prefill overwrites.  Returns (cache', next (S,))."""
        import jax.numpy as jnp
        from ..kernels.flash_attention import decode_attention
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        S = last_tokens.shape[0]
        C = H * D
        caches = list(cache)
        rows = jnp.arange(S)

        def body():
            pos_nd = NDArray(positions.reshape(S, 1))
            x = self.block.embed(NDArray(last_tokens)) \
                + self.block.pos_embed(pos_nd)
            h = self.block.drop(x)
            for l, cell in enumerate(self._cells):
                at = cell.attention
                hn = cell.ln1(h)
                q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                qh = q._data.reshape(S, H, D)
                knh = kn._data.reshape(S, H, D)
                vnh = vn._data.reshape(S, H, D)
                ck = caches[l].at[rows, :, positions].set(
                    knh.astype(caches[l].dtype))
                cv = caches[L + l].at[rows, :, positions].set(
                    vnh.astype(caches[L + l].dtype))
                caches[l], caches[L + l] = ck, cv
                attn = decode_attention(qh, ck, cv, positions)
                out_nd = NDArray(attn.reshape(S, 1, C).astype(h._data.dtype))
                h = h + at.dropout(at.proj(out_nd))
                h = h + cell._ffn_out(cell.ln2(h))
            logits = self.block._project(self.block.ln_f(h))
            return logits._data

        logits = self._with_params(param_vals, aux_vals, key, body)
        lg = logits[:, 0, :]
        nxt = self._sample_step(lg, positions + 1, samp)
        out = (tuple(caches), nxt)
        if self._health_on:
            out = out + (_health.decode_health(lg),)
        if self.logprobs_topn:
            from .sampling import topn_logprobs
            out = out + (topn_logprobs(lg, samp[3], self.logprobs_topn),)
        return out

    def _decode_burst_pure(self, cache, last_tokens, positions, budgets,
                           eos_ids, done0, samp,
                           param_vals, aux_vals, key):
        """``scan_steps`` decode steps captured as ONE program
        (:func:`jax.lax.scan` over the exact :meth:`_decode_pure` cell
        body) with in-program termination riding the carry.

        Per slot: ``budgets`` (S,) int32 caps the tokens this burst may
        emit (the request's remaining budget), ``eos_ids`` (S,) int32 is
        the stop token (-1: none), ``done0`` (S,) bool marks slots that
        must not emit at all (free slots).  A slot whose step hits EOS or
        exhausts its budget flips ``done``; from then on its
        ``(last_token, position)`` carry is FROZEN, so every subsequent
        step recomputes — and rewrites, bit-for-bit — the same K/V at
        the same position (per-slot rows are independent, so the rewrite
        is exactly idempotent and a mid-burst EOS cannot corrupt the
        cache).  Live slots are untouched by their neighbors' freezes:
        the token stream is bit-identical to ``scan_steps`` per-step
        :meth:`_decode_pure` dispatches.

        Returns ``(cache', tokens (k, S), emitted (S,))`` — row ``j`` of
        ``tokens`` is step ``j``'s argmax; slot ``s``'s valid prefix is
        ``tokens[:emitted[s], s]``.  With the health plane on, the
        per-step logit stats are folded across the burst in-program
        (max / mean / all) to the same (S,) triplet one decode returns."""
        import jax.numpy as jnp
        from jax import lax
        from ..kernels.flash_attention import decode_attention
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        S = last_tokens.shape[0]
        C = H * D
        k = int(self.scan_steps)
        rows = jnp.arange(S)

        def run_scan():
            def step(carry, _):
                caches, lt, pos, done, emitted = carry
                caches = list(caches)
                pos_nd = NDArray(pos.reshape(S, 1))
                x = self.block.embed(NDArray(lt)) \
                    + self.block.pos_embed(pos_nd)
                h = self.block.drop(x)
                for l, cell in enumerate(self._cells):
                    at = cell.attention
                    hn = cell.ln1(h)
                    q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                    qh = q._data.reshape(S, H, D)
                    knh = kn._data.reshape(S, H, D)
                    vnh = vn._data.reshape(S, H, D)
                    ck = caches[l].at[rows, :, pos].set(
                        knh.astype(caches[l].dtype))
                    cv = caches[L + l].at[rows, :, pos].set(
                        vnh.astype(caches[L + l].dtype))
                    caches[l], caches[L + l] = ck, cv
                    attn = decode_attention(qh, ck, cv, pos)
                    out_nd = NDArray(attn.reshape(S, 1, C).astype(
                        h._data.dtype))
                    h = h + at.dropout(at.proj(out_nd))
                    h = h + cell._ffn_out(cell.ln2(h))
                logits = self.block._project(self.block.ln_f(h))
                lg = logits._data[:, 0, :]
                # keyed at pos + 1 (the position this token will
                # occupy): the carry IS the per-step key split
                nxt = self._sample_step(lg, pos + 1, samp)
                emit = ~done
                emitted2 = emitted + emit.astype(jnp.int32)
                done2 = done | (emit & ((nxt == eos_ids)
                                        | (emitted2 >= budgets)))
                lt2 = jnp.where(done2[:, None], lt, nxt[:, None])
                pos2 = jnp.where(done2, pos, pos + 1)
                ys = (nxt,) if not self._health_on \
                    else (nxt,) + _health.decode_health(lg)
                if self.logprobs_topn:
                    from .sampling import topn_logprobs
                    ys = ys + topn_logprobs(lg, samp[3],
                                            self.logprobs_topn)
                return (tuple(caches), lt2, pos2, done2, emitted2), ys

            carry0 = (cache, last_tokens, positions, done0,
                      jnp.zeros(S, jnp.int32))
            return lax.scan(step, carry0, None, length=k)

        (caches, _, _, _, emitted), ys = self._with_params(
            param_vals, aux_vals, key, run_scan)
        ys = list(ys)
        if self.logprobs_topn:            # stacked (k, S, N) per burst
            lpi = ys.pop()
            lpv = ys.pop()
        if self._health_on:
            toks, lmax, ent, fin = ys
            # frozen steps replay their final live step's logits, so the
            # fold is dominated by live emissions (max/all exact, mean
            # slightly weighted toward the freeze value)
            out = (caches, toks, emitted,
                   (lmax.max(axis=0), ent.mean(axis=0), fin.all(axis=0)))
        else:
            (toks,) = ys
            out = (caches, toks, emitted)
        if self.logprobs_topn:
            out = out + ((lpv, lpi),)
        return out

    def _verify_pure(self, cache, tokens, positions, samp,
                     param_vals, aux_vals, key):
        """The speculative-decode VERIFY program: a k+1-wide
        generalization of :meth:`_decode_pure`.  ``tokens`` (S, Q) int32
        — row 0 is each slot's last accepted token, rows 1..Q-1 the
        draft's proposals; ``positions`` (S,) int32 the base write head.
        Scatters Q K/V writes per slot per layer (positions past
        ``max_len`` drop — overrun rows near the budget edge must not
        stomp a live entry), attends via
        :func:`kernels.flash_attention.verify_decode_attention`, and
        returns (cache', argmax (S, Q)): the target's next token AFTER
        each of the Q positions.  With Q == 1 this is exactly decode."""
        import jax.numpy as jnp
        from ..kernels.flash_attention import verify_decode_attention
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        S, Q = tokens.shape
        C = H * D
        caches = list(cache)
        rows = jnp.arange(S)
        pos_q = positions[:, None] \
            + jnp.arange(Q, dtype=jnp.int32)[None, :]          # (S, Q)

        def body():
            pos_nd = NDArray(jnp.minimum(pos_q, self.max_len - 1))
            x = self.block.embed(NDArray(tokens)) \
                + self.block.pos_embed(pos_nd)
            h = self.block.drop(x)
            for l, cell in enumerate(self._cells):
                at = cell.attention
                hn = cell.ln1(h)
                q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                qh = q._data.reshape(S, Q, H, D).transpose(0, 2, 1, 3)
                knh = kn._data.reshape(S, Q, H, D)
                vnh = vn._data.reshape(S, Q, H, D)
                ck = caches[l].at[rows[:, None], :, pos_q].set(
                    knh.astype(caches[l].dtype), mode="drop")
                cv = caches[L + l].at[rows[:, None], :, pos_q].set(
                    vnh.astype(caches[L + l].dtype), mode="drop")
                caches[l], caches[L + l] = ck, cv
                attn = verify_decode_attention(qh, ck, cv, positions)
                out_nd = NDArray(attn.transpose(0, 2, 1, 3).reshape(
                    S, Q, C).astype(h._data.dtype))
                h = h + at.dropout(at.proj(out_nd))
                h = h + cell._ffn_out(cell.ln2(h))
            logits = self.block._project(self.block.ln_f(h))
            return logits._data

        logits = self._with_params(param_vals, aux_vals, key, body)
        nxt = self._sample_verify(logits, pos_q, samp)
        if self.logprobs_topn:
            from .sampling import topn_logprobs
            lp = topn_logprobs(logits, samp[3][:, None, :],
                               self.logprobs_topn)
            return tuple(caches), nxt, lp
        return tuple(caches), nxt

    # -- pure programs, paged layout ------------------------------------
    def _scatter_block(self, pool, hslice, table, idx, traced_idx):
        """Write an (H, w, D) strip into block ``table[idx]`` of a
        (num_blocks, H, block_size, D) pool.  ``idx`` may be traced
        (``traced_idx``) — out-of-range indices redirect to the null
        block 0, where padded-garbage writes are harmless."""
        import jax.numpy as jnp
        from jax import lax
        NB = self.max_blocks_per_slot
        if traced_idx:
            blk = jnp.where(idx < NB,
                            jnp.take(table, jnp.minimum(idx, NB - 1)), 0)
        else:
            blk = table[idx]
        return lax.dynamic_update_slice(
            pool, hslice[None].astype(pool.dtype), (blk, 0, 0, 0))

    def _prefill_paged_pure(self, cache, tokens, n_valid, table, samp,
                            param_vals, aux_vals, key):
        """Prefix-cache MISS prefill: the exact dense prefill body (so
        paged == dense bit-for-bit), with the slot's K/V scattered into
        the blocks named by ``table`` (max_blocks,) int32 instead of a
        dense row.  Positions past the table's reservation redirect to
        the null block."""
        import jax.numpy as jnp
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        Tb = tokens.shape[1]
        bs = self.block_size

        def body():
            x = self.block._embed_at(NDArray(tokens))
            ks, vs = [], []
            for cell in self._cells:
                x, k, v = cell.prime(x)
                ks.append(k._data)
                vs.append(v._data)
            logits = self.block._project(self.block.ln_f(x))
            return logits._data, ks, vs

        logits, ks, vs = self._with_params(param_vals, aux_vals, key, body)
        out = list(cache)
        for l in range(L):
            kh = ks[l].reshape(Tb, H, D).transpose(1, 0, 2)
            vh = vs[l].reshape(Tb, H, D).transpose(1, 0, 2)
            for j in range(-(-Tb // bs)):
                out[l] = self._scatter_block(
                    out[l], kh[:, j * bs:(j + 1) * bs], table, j, False)
                out[L + l] = self._scatter_block(
                    out[L + l], vh[:, j * bs:(j + 1) * bs], table, j, False)
        last = jnp.take(logits[0], n_valid - 1, axis=0)
        first, lp = self._sample_prefill(last, n_valid, samp)
        if lp is not None:
            return tuple(out), first, lp
        return tuple(out), first

    def _prefill_ext_pure(self, cache, tokens, n_valid, ctx, table, samp,
                          param_vals, aux_vals, key):
        """Prefix-cache HIT prefill: ``ctx`` leading positions (always a
        multiple of block_size) already hold valid K/V in shared blocks;
        run the transformer over only the SUFFIX ``tokens`` (1, Tb),
        appending K/V at positions [ctx, ctx+Tb) and attending through
        the block table — same manual body as decode, widened to Tb query
        rows.  ``ctx`` is an int32 operand, so one program per suffix
        bucket serves every hit length."""
        import jax.numpy as jnp
        import math as _math
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        Tb = tokens.shape[1]
        bs = self.block_size
        T = self.max_blocks_per_slot * bs
        C = H * D
        scale = 1.0 / _math.sqrt(D)
        caches = list(cache)

        def body():
            pos = jnp.minimum(ctx + jnp.arange(Tb, dtype=jnp.int32),
                              self.max_len - 1)[None]          # (1, Tb)
            x = self.block.embed(NDArray(tokens)) \
                + self.block.pos_embed(NDArray(pos))
            h = self.block.drop(x)
            q_idx = jnp.arange(Tb, dtype=jnp.int32)
            key_idx = jnp.arange(T, dtype=jnp.int32)
            live = key_idx[None, :] <= (ctx + q_idx)[:, None]  # (Tb, T)
            for l, cell in enumerate(self._cells):
                at = cell.attention
                hn = cell.ln1(h)
                q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                qh = q._data.reshape(Tb, H, D).transpose(1, 0, 2)[None]
                knh = kn._data.reshape(Tb, H, D).transpose(1, 0, 2)
                vnh = vn._data.reshape(Tb, H, D).transpose(1, 0, 2)
                j0 = ctx // bs
                for j in range(-(-Tb // bs)):
                    caches[l] = self._scatter_block(
                        caches[l], knh[:, j * bs:(j + 1) * bs],
                        table, j0 + j, True)
                    caches[L + l] = self._scatter_block(
                        caches[L + l], vnh[:, j * bs:(j + 1) * bs],
                        table, j0 + j, True)
                # gather this slot's whole logical strip and attend
                # (mirrors _sdpa's stable-softmax arithmetic)
                ck = jnp.moveaxis(caches[l][table], 1, 0).reshape(
                    1, H, T, D)
                cv = jnp.moveaxis(caches[L + l][table], 1, 0).reshape(
                    1, H, T, D)
                s = jnp.einsum("bhqd,bhkd->bhqk", qh, ck) * scale
                s = jnp.where(live[None, None], s, -1e30)
                m = jnp.max(s, axis=-1, keepdims=True)
                p = jnp.exp(s - m)
                lsum = jnp.sum(p, axis=-1, keepdims=True)
                attn = jnp.einsum("bhqk,bhkd->bhqd",
                                  (p / lsum).astype(cv.dtype), cv)
                out_nd = NDArray(attn.transpose(0, 2, 1, 3).reshape(
                    1, Tb, C).astype(h._data.dtype))
                h = h + at.dropout(at.proj(out_nd))
                h = h + cell._ffn_out(cell.ln2(h))
            logits = self.block._project(self.block.ln_f(h))
            return logits._data

        logits = self._with_params(param_vals, aux_vals, key, body)
        last = jnp.take(logits[0], n_valid - 1, axis=0)
        first, lp = self._sample_prefill(last, ctx + n_valid, samp)
        if lp is not None:
            return tuple(caches), first, lp
        return tuple(caches), first

    def _decode_paged_pure(self, cache, last_tokens, positions, tables,
                           samp, param_vals, aux_vals, key):
        """The decode program, paged: identical to :meth:`_decode_pure`
        except each slot's K/V write lands in block ``tables[s, pos//bs]``
        at offset ``pos % bs`` and attention reads through
        :func:`paged_decode_attention`.  ``tables`` (S, max_blocks) int32
        is an operand — join/leave never recompiles."""
        import jax.numpy as jnp
        from ..kernels.flash_attention import paged_decode_attention
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        S = last_tokens.shape[0]
        C = H * D
        bs = self.block_size
        caches = list(cache)
        rows = jnp.arange(S)
        blk = tables[rows, positions // bs]                    # (S,)
        off = positions % bs                                   # (S,)

        def body():
            pos_nd = NDArray(positions.reshape(S, 1))
            x = self.block.embed(NDArray(last_tokens)) \
                + self.block.pos_embed(pos_nd)
            h = self.block.drop(x)
            for l, cell in enumerate(self._cells):
                at = cell.attention
                hn = cell.ln1(h)
                q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                qh = q._data.reshape(S, H, D)
                knh = kn._data.reshape(S, H, D)
                vnh = vn._data.reshape(S, H, D)
                ck = caches[l].at[blk, :, off].set(
                    knh.astype(caches[l].dtype))
                cv = caches[L + l].at[blk, :, off].set(
                    vnh.astype(caches[L + l].dtype))
                caches[l], caches[L + l] = ck, cv
                attn = paged_decode_attention(qh, ck, cv, tables, positions)
                out_nd = NDArray(attn.reshape(S, 1, C).astype(h._data.dtype))
                h = h + at.dropout(at.proj(out_nd))
                h = h + cell._ffn_out(cell.ln2(h))
            logits = self.block._project(self.block.ln_f(h))
            return logits._data

        logits = self._with_params(param_vals, aux_vals, key, body)
        lg = logits[:, 0, :]
        nxt = self._sample_step(lg, positions + 1, samp)
        out = (tuple(caches), nxt)
        if self._health_on:
            out = out + (_health.decode_health(lg),)
        if self.logprobs_topn:
            from .sampling import topn_logprobs
            out = out + (topn_logprobs(lg, samp[3], self.logprobs_topn),)
        return out

    def _decode_burst_paged_pure(self, cache, last_tokens, positions,
                                 budgets, eos_ids, done0, tables, samp,
                                 param_vals, aux_vals, key):
        """:meth:`_decode_burst_pure` over the paged layout: the scanned
        step is the exact :meth:`_decode_paged_pure` cell body, and a
        frozen (done) slot's K/V writes are redirected to the null block
        0 — belt on top of the idempotent-rewrite argument, so a
        finished slot's replayed steps can never touch a live block, its
        own or (through any future sharing scheme) anyone else's.
        Decode positions sit strictly past the shared prompt blocks, so
        the burst composes with the BlockPool prefix cache unchanged."""
        import jax.numpy as jnp
        from jax import lax
        from ..kernels.flash_attention import paged_decode_attention
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        S = last_tokens.shape[0]
        C = H * D
        bs = self.block_size
        k = int(self.scan_steps)
        rows = jnp.arange(S)

        def run_scan():
            def step(carry, _):
                caches, lt, pos, done, emitted = carry
                caches = list(caches)
                blk = jnp.where(done, 0, tables[rows, pos // bs])  # (S,)
                off = pos % bs                                     # (S,)
                pos_nd = NDArray(pos.reshape(S, 1))
                x = self.block.embed(NDArray(lt)) \
                    + self.block.pos_embed(pos_nd)
                h = self.block.drop(x)
                for l, cell in enumerate(self._cells):
                    at = cell.attention
                    hn = cell.ln1(h)
                    q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                    qh = q._data.reshape(S, H, D)
                    knh = kn._data.reshape(S, H, D)
                    vnh = vn._data.reshape(S, H, D)
                    ck = caches[l].at[blk, :, off].set(
                        knh.astype(caches[l].dtype))
                    cv = caches[L + l].at[blk, :, off].set(
                        vnh.astype(caches[L + l].dtype))
                    caches[l], caches[L + l] = ck, cv
                    attn = paged_decode_attention(qh, ck, cv, tables, pos)
                    out_nd = NDArray(attn.reshape(S, 1, C).astype(
                        h._data.dtype))
                    h = h + at.dropout(at.proj(out_nd))
                    h = h + cell._ffn_out(cell.ln2(h))
                logits = self.block._project(self.block.ln_f(h))
                lg = logits._data[:, 0, :]
                # keyed at pos + 1 (the position this token will
                # occupy): the carry IS the per-step key split
                nxt = self._sample_step(lg, pos + 1, samp)
                emit = ~done
                emitted2 = emitted + emit.astype(jnp.int32)
                done2 = done | (emit & ((nxt == eos_ids)
                                        | (emitted2 >= budgets)))
                lt2 = jnp.where(done2[:, None], lt, nxt[:, None])
                pos2 = jnp.where(done2, pos, pos + 1)
                ys = (nxt,) if not self._health_on \
                    else (nxt,) + _health.decode_health(lg)
                if self.logprobs_topn:
                    from .sampling import topn_logprobs
                    ys = ys + topn_logprobs(lg, samp[3],
                                            self.logprobs_topn)
                return (tuple(caches), lt2, pos2, done2, emitted2), ys

            carry0 = (cache, last_tokens, positions, done0,
                      jnp.zeros(S, jnp.int32))
            return lax.scan(step, carry0, None, length=k)

        (caches, _, _, _, emitted), ys = self._with_params(
            param_vals, aux_vals, key, run_scan)
        ys = list(ys)
        if self.logprobs_topn:
            lpi = ys.pop()
            lpv = ys.pop()
        if self._health_on:
            toks, lmax, ent, fin = ys
            out = (caches, toks, emitted,
                   (lmax.max(axis=0), ent.mean(axis=0), fin.all(axis=0)))
        else:
            (toks,) = ys
            out = (caches, toks, emitted)
        if self.logprobs_topn:
            out = out + ((lpv, lpi),)
        return out

    def _verify_paged_pure(self, cache, tokens, positions, tables, samp,
                           param_vals, aux_vals, key):
        """The verify program, paged: :meth:`_verify_pure` with each
        slot's Q writes routed through its block table.  Positions past a
        slot's reservation (table padding) or past ``max_len`` redirect
        to the null block — overrun rows near the budget edge land in
        the sink, never in a neighbor's block."""
        import jax.numpy as jnp
        from ..kernels.flash_attention import paged_verify_decode_attention
        L, H, D = self.num_layers, self.num_heads, self.head_dim
        S, Q = tokens.shape
        C = H * D
        bs = self.block_size
        NB = self.max_blocks_per_slot
        caches = list(cache)
        rows = jnp.arange(S)
        pos_q = positions[:, None] \
            + jnp.arange(Q, dtype=jnp.int32)[None, :]          # (S, Q)
        col = pos_q // bs
        ok = (col < NB) & (pos_q < self.max_len)
        blk = jnp.where(ok, tables[rows[:, None],
                                   jnp.minimum(col, NB - 1)], 0)  # (S, Q)
        off = pos_q % bs                                          # (S, Q)

        def body():
            pos_nd = NDArray(jnp.minimum(pos_q, self.max_len - 1))
            x = self.block.embed(NDArray(tokens)) \
                + self.block.pos_embed(pos_nd)
            h = self.block.drop(x)
            for l, cell in enumerate(self._cells):
                at = cell.attention
                hn = cell.ln1(h)
                q, kn, vn = at.query(hn), at.key(hn), at.value(hn)
                qh = q._data.reshape(S, Q, H, D).transpose(0, 2, 1, 3)
                knh = kn._data.reshape(S, Q, H, D)
                vnh = vn._data.reshape(S, Q, H, D)
                ck = caches[l].at[blk, :, off].set(
                    knh.astype(caches[l].dtype))
                cv = caches[L + l].at[blk, :, off].set(
                    vnh.astype(caches[L + l].dtype))
                caches[l], caches[L + l] = ck, cv
                attn = paged_verify_decode_attention(qh, ck, cv, tables,
                                                     positions)
                out_nd = NDArray(attn.transpose(0, 2, 1, 3).reshape(
                    S, Q, C).astype(h._data.dtype))
                h = h + at.dropout(at.proj(out_nd))
                h = h + cell._ffn_out(cell.ln2(h))
            logits = self.block._project(self.block.ln_f(h))
            return logits._data

        logits = self._with_params(param_vals, aux_vals, key, body)
        nxt = self._sample_verify(logits, pos_q, samp)
        if self.logprobs_topn:
            from .sampling import topn_logprobs
            lp = topn_logprobs(logits, samp[3][:, None, :],
                               self.logprobs_topn)
            return tuple(caches), nxt, lp
        return tuple(caches), nxt

    # -- cache lifecycle ------------------------------------------------
    def reset(self):
        """(Re)allocate the cache: all slots free, all rows zero.  Called
        at construction and by the continuous batcher after a watchdog
        restart (a replaced worker must not trust donated buffers that a
        dying dispatch may have consumed).  Paged mode also rewipes the
        block pool, every block table, and the prefix cache — cached K/V
        must never outlive the params that computed it."""
        import jax.numpy as jnp
        if getattr(self, "draft", None) is not None:
            self.draft.reset()
        self._samp_dev = None
        if self.paged:
            N, H, bs, D = (self.num_blocks, self.num_heads,
                           self.block_size, self.head_dim)
            self._cache = tuple(jnp.zeros((N, H, bs, D), jnp.float32)
                                for _ in range(2 * self.num_layers))
            self.pool.reset()
            # bytes behind one block across all layers — lets the pool
            # report occupancy in bytes (device-memory attribution)
            self.pool.block_bytes = self.cache_bytes // self.num_blocks
            self._slot_blocks = [[] for _ in range(self.max_slots)]
            self._tables = _np.zeros(
                (self.max_slots, self.max_blocks_per_slot), _np.int32)
            self._tables_dev = None
            return
        S, H, T, D = (self.max_slots, self.num_heads, self.max_len,
                      self.head_dim)
        self._cache = tuple(jnp.zeros((S, H, T, D), jnp.float32)
                            for _ in range(2 * self.num_layers))

    @property
    def cache_bytes(self) -> int:
        return sum(int(c.size) * c.dtype.itemsize for c in self._cache)

    # DynamicBatcher compatibility: the slot count plays the role of the
    # batch cap, the prefill buckets the role of the shape buckets
    @property
    def max_batch_size(self) -> int:
        return self.max_slots

    @property
    def buckets(self):
        return self.prefill_buckets

    def prefill_bucket_for(self, n: int) -> Optional[int]:
        for b in self.prefill_buckets:
            if b >= int(n):
                return b
        return None

    # -- host-side dispatch ---------------------------------------------
    def _guarded(self, call, *args):
        param_vals, aux_vals = self._param_fn()
        from .. import random as _random
        key = _random.new_key(self._ctx)
        try:
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                return call(self._cache, *args, param_vals, aux_vals, key)
        except Exception as e:
            # RESOURCE_EXHAUSTED here is the device running out of HBM
            # mid-dispatch: publish the oom FAULT so the flight recorder
            # writes one debounced postmortem carrying the memory
            # breakdown, program inventory, and per-slot KV occupancy.
            if _telemetry_device.is_oom(e):
                _telemetry_device.report_oom("serving." + self.name, e,
                                             model=self.name)
            raise

    def _unpack_prefill(self, out) -> int:
        """Rebind the cache and stash the prefill logprobs (arity is
        baked by ``logprobs_topn``, exactly like the health plane)."""
        if self.logprobs_topn:
            cache, first, lp = out
            self._last_prefill_logprobs = tuple(_np.asarray(a)
                                                for a in lp)
        else:
            cache, first = out
            self._last_prefill_logprobs = None
        self._cache = cache
        return int(first)

    def prefill(self, tokens, slot: int,
                reserve_tokens: Optional[int] = None) -> int:
        """Admit a prompt into ``slot``: pad to the prompt-length bucket,
        dispatch the bucket's prefill program, return the FIRST generated
        token.  After this the slot's write head is at ``len(tokens)``
        (the returned token's K/V lands there on its first decode).

        Paged mode allocates the slot's block table first —
        ``reserve_tokens`` (default ``max_len``) is the worst-case total
        positions (prompt + budget) the request may ever write, so decode
        NEVER allocates and can never fail mid-flight.  A prefix-cache
        hit dispatches the suffix program instead, skipping the shared
        span's prefill work entirely."""
        import jax.numpy as jnp
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        n = int(toks.shape[0])
        if not 0 <= int(slot) < self.max_slots:
            raise MXNetError(f"{self.name}: slot {slot} out of range "
                             f"(max_slots {self.max_slots})")
        if n < 1:
            raise MXNetError(f"{self.name}: empty prompt")
        if n > self.max_len - 1:
            raise MXNetError(
                f"{self.name}: prompt length {n} leaves no room to "
                f"generate (max_len {self.max_len})")
        if self.draft is not None:
            # The draft mirrors the target's slot layout: prefill it with
            # the same prompt so its write head tracks ours.  Its own
            # first-token output is discarded — only the target's argmax
            # is ever emitted.  Reserve spec_k extra positions on BOTH
            # engines: a verify near the budget edge writes up to k
            # positions past the last consumed token.
            self.draft._warming = self._warming
            self.draft.prefill(toks, slot,
                               reserve_tokens=int(
                                   reserve_tokens or self.max_len)
                               + self.spec_k)
        if not self.paged:
            bucket = self.prefill_bucket_for(n)
            padded = _np.zeros((1, bucket), _np.int32)
            padded[0, :n] = toks
            with _telemetry.trace_span("serve.prefill", cat="serving",
                                       model=self.name, slot=int(slot),
                                       tokens=n, bucket=bucket):
                out = self._guarded(
                    self._prefill, jnp.asarray(padded),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(int(slot), jnp.int32),
                    self._slot_samp(slot))
            return self._unpack_prefill(out)
        slot = int(slot)
        if self._slot_blocks[slot]:
            self.release_slot(slot)
        reserve = int(reserve_tokens or self.max_len) \
            + (self.spec_k if self.draft is not None else 0)
        reserve = max(n + 1, min(reserve, self.max_len))
        table, m = self.pool.allocate(toks, n, reserve,
                                      share=not self._warming)
        self._slot_blocks[slot] = table
        row = _np.zeros(self.max_blocks_per_slot, _np.int32)
        row[:len(table)] = table
        self._tables[slot] = row
        self._tables_dev = None
        try:
            return self._prefill_paged_dispatch(toks, n, m, row, slot)
        except Exception:
            # The fresh (non-shared) blocks never got their K/V written;
            # allocate() already registered the full ones in the prefix
            # cache, so unregister them before release parks them idle —
            # a later same-prefix request must prefill cold, not "hit"
            # garbage.
            self.pool.invalidate(table[m // self.pool.block_size:])
            self.release_slot(slot)
            raise

    def _prefill_paged_dispatch(self, toks, n: int, m: int, row,
                                slot: int) -> int:
        import jax.numpy as jnp
        ss = self._slot_samp(slot)
        if m == 0:
            bucket = self.prefill_bucket_for(n)
            padded = _np.zeros((1, bucket), _np.int32)
            padded[0, :n] = toks
            with _telemetry.trace_span("serve.prefill", cat="serving",
                                       model=self.name, slot=slot,
                                       tokens=n, bucket=bucket):
                out = self._guarded(
                    self._prefill, jnp.asarray(padded),
                    jnp.asarray(n, jnp.int32), jnp.asarray(row), ss)
        else:
            sn = n - m
            bucket = self.prefill_bucket_for(sn)
            padded = _np.zeros((1, bucket), _np.int32)
            padded[0, :sn] = toks[m:]
            with _telemetry.trace_span("serve.prefill", cat="serving",
                                       model=self.name, slot=slot,
                                       tokens=n, bucket=bucket,
                                       prefix_hit_tokens=m):
                out = self._guarded(
                    self._prefill_ext, jnp.asarray(padded),
                    jnp.asarray(sn, jnp.int32), jnp.asarray(m, jnp.int32),
                    jnp.asarray(row), ss)
        return self._unpack_prefill(out)

    def decode(self, last_tokens, positions):
        """Advance EVERY slot one position in one dispatch: last_tokens
        (S,) int32 (free slots: 0), positions (S,) int32 (free slots: 0).
        Returns the next token per slot as a host int32 array."""
        import jax.numpy as jnp
        lt = jnp.asarray(_np.asarray(last_tokens, _np.int32).reshape(
            self.max_slots, 1))
        pos = jnp.asarray(_np.asarray(positions, _np.int32).reshape(
            self.max_slots))
        if self.paged:
            if self._tables_dev is None:
                self._tables_dev = jnp.asarray(self._tables)
            out = self._guarded(self._decode, lt, pos, self._tables_dev,
                                self._samp_tuple())
        else:
            out = self._guarded(self._decode, lt, pos,
                                self._samp_tuple())
        out = list(out)
        if self.logprobs_topn:
            self._last_logprobs = tuple(_np.asarray(a)
                                        for a in out.pop())
        if self._health_on:
            self._last_decode_health = out.pop()
        cache, nxt = out
        self._cache = cache
        return _np.asarray(nxt)

    def decode_burst(self, last_tokens, positions, budgets, eos_ids,
                     active):
        """Advance every slot up to ``scan_steps`` positions in ONE
        dispatch (docs/serving.md "Multi-token decode bursts"):
        ``last_tokens``/``positions`` (S,) int32 as in :meth:`decode`,
        ``budgets`` (S,) int32 the per-slot cap on tokens this burst may
        emit, ``eos_ids`` (S,) int32 the per-slot stop token (-1: none),
        ``active`` (S,) bool False for free slots.  Returns host arrays
        ``(tokens (k, S) int32, emitted (S,) int32)``; slot ``s``'s
        emitted tokens are ``tokens[:emitted[s], s]``, bit-identical to
        the same number of per-step :meth:`decode` calls."""
        import jax.numpy as jnp
        k = int(self.scan_steps)
        if k < 1:
            raise MXNetError(
                f"{self.name}: decode bursts disabled (scan_steps "
                f"{self.scan_steps}; set MXNET_DECODE_SCAN_STEPS >= 1)")
        S = self.max_slots
        lt = jnp.asarray(_np.asarray(last_tokens, _np.int32).reshape(S, 1))
        pos = jnp.asarray(_np.asarray(positions, _np.int32).reshape(S))
        bud = jnp.asarray(_np.asarray(budgets, _np.int32).reshape(S))
        eos = jnp.asarray(_np.asarray(eos_ids, _np.int32).reshape(S))
        done0 = jnp.asarray(
            ~_np.asarray(active, bool).reshape(S))
        if self.paged:
            if self._tables_dev is None:
                self._tables_dev = jnp.asarray(self._tables)
            out = self._guarded(self._decode_burst, lt, pos, bud, eos,
                                done0, self._tables_dev,
                                self._samp_tuple())
        else:
            out = self._guarded(self._decode_burst, lt, pos, bud, eos,
                                done0, self._samp_tuple())
        out = list(out)
        if self.logprobs_topn:          # (k, S, N) per burst step
            self._last_logprobs = tuple(_np.asarray(a)
                                        for a in out.pop())
        if self._health_on:
            self._last_decode_health = out.pop()
        cache, toks, emitted = out
        self._cache = cache
        return _np.asarray(toks), _np.asarray(emitted)

    def last_decode_health(self):
        """Device arrays from the most recent decode dispatch when the
        health plane is on (``(logit_max (S,), entropy (S,), finite
        (S,))`` — see :func:`health.decode_health`), else None.  The
        token read in :meth:`decode` already synced the dispatch, so
        pulling these is free of extra device round-trips."""
        return self._last_decode_health

    # -- speculative decoding -------------------------------------------
    def attach_draft(self, draft: "GenerationEngine",
                     spec_k: Optional[int] = None) -> None:
        """Attach a (small) draft engine for speculative decoding.

        The draft proposes ``spec_k`` tokens per slot (default
        ``MXNET_SPEC_K``); the target scores all ``spec_k + 1`` positions
        in ONE verify dispatch.  The draft must mirror the target's slot
        layout and position space — same ``max_slots``, ``max_len`` at
        least the target's, same vocabulary (argmax ids are compared).
        Attach BEFORE :meth:`warmup` so the verify program joins the
        warmed set."""
        from ..base import getenv_int
        if draft is self:
            raise MXNetError(f"{self.name}: a model cannot draft itself")
        if int(draft.max_slots) != self.max_slots:
            raise MXNetError(
                f"{self.name}: draft max_slots {draft.max_slots} != "
                f"target max_slots {self.max_slots}")
        if int(draft.max_len) < self.max_len:
            raise MXNetError(
                f"{self.name}: draft max_len {draft.max_len} < target "
                f"max_len {self.max_len} (the draft decodes at the same "
                f"positions)")
        tv = getattr(self.block, "_vocab_size", None)
        dv = getattr(draft.block, "_vocab_size", None)
        if tv is not None and dv is not None and int(tv) != int(dv):
            raise MXNetError(
                f"{self.name}: draft vocab {dv} != target vocab {tv}")
        k = int(spec_k if spec_k is not None
                else getenv_int("MXNET_SPEC_K", 4))
        if k < 1:
            raise MXNetError(f"spec_k must be >= 1, got {k}")
        self.draft = draft
        self.spec_k = k
        # draft outputs are never surfaced (only target verify columns
        # are emitted), so zero its logprobs top-N before its first
        # dispatch bakes the output arity — spec bursts skip the extra
        # per-step top_k work entirely
        if draft.compiled_programs() == 0:
            draft.logprobs_topn = 0
        # scan the k autoregressive draft decodes into one dispatch
        # (spec drops from k+1 to 2 dispatches per burst).  The draft's
        # burst width must equal spec_k, so override its default here —
        # before warmup bakes the trace.  scan_steps == 0 (the
        # MXNET_DECODE_SCAN_STEPS kill switch) keeps the host loop.
        if draft.scan_steps != 0:
            draft.scan_steps = k

    def verify(self, tokens, positions):
        """Score ``spec_k + 1`` positions for EVERY slot in one dispatch:
        ``tokens`` (S, Q) int32 — column 0 each slot's last accepted
        token, columns 1..Q-1 the draft proposals; ``positions`` (S,)
        int32 base write heads.  Returns the target's argmax (S, Q) as a
        host array: ``out[s, j]`` is the next token after consuming
        ``tokens[s, :j + 1]``."""
        import jax.numpy as jnp
        toks = _np.asarray(tokens, _np.int32).reshape(self.max_slots, -1)
        lt = jnp.asarray(toks)
        pos = jnp.asarray(_np.asarray(positions, _np.int32).reshape(
            self.max_slots))
        if self.paged:
            if self._tables_dev is None:
                self._tables_dev = jnp.asarray(self._tables)
            res = self._guarded(self._verify, lt, pos,
                                self._tables_dev, self._samp_tuple())
        else:
            res = self._guarded(self._verify, lt, pos,
                                self._samp_tuple())
        if self.logprobs_topn:          # (S, Q, N) per verify
            cache, out, lp = res
            self._last_verify_logprobs = tuple(_np.asarray(a)
                                               for a in lp)
        else:
            cache, out = res
            self._last_verify_logprobs = None
        self._cache = cache
        return _np.asarray(out)

    def spec_step(self, last_tokens, positions):
        """One speculative step for EVERY slot: the draft proposes
        ``spec_k`` tokens autoregressively — ONE scanned draft dispatch
        when its burst program is enabled (the default; ``spec_k`` host
        dispatches otherwise) — then ONE target verify dispatch scores
        all ``spec_k + 1`` positions.

        Acceptance is **Gumbel-coupled stochastic speculative
        sampling**.  Both engines sample with the SAME per-slot root
        key and position-indexed key stream (:meth:`set_slot_sampling`
        cascades to the draft), so at every position they share one
        gumbel noise vector; the verify program returns the target's
        keyed sample at each position, and acceptance is the longest
        prefix where the draft's sample equals the target's.  Every
        emitted token is a target sample under the target's own
        filtered distribution, and because the key depends only on
        (root, position), each one is bit-identical to what a no-draft
        sampled run emits at that position — at ANY accept rate.  This
        is the shared-noise form of the accept/reject + residual
        resample scheme (distributionally equivalent: the coupled
        target sample IS the residual draw when the proposals
        diverge), and greedy acceptance is its ``temperature == 0``
        special case, where sample == argmax on both sides.

        Returns ``(out, accepted)``: ``out`` (S, spec_k + 1) int32 —
        ``out[s, :accepted[s] + 1]`` are this step's emitted tokens,
        every one of them a target sample (bit-identical to plain
        decode by construction); ``accepted`` (S,) int64 in
        ``[0, spec_k]`` counts the draft tokens accepted per slot.
        Rejected positions' K/V is rolled back: the cursor simply does
        not advance past them (stale entries are masked and then
        overwritten by the next dispatch at the same position), and in
        paged mode the pool's :meth:`~.kvcache.BlockPool.rewind` COW
        guard keeps the overwrite out of any shared block."""
        if self.draft is None:
            raise MXNetError(f"{self.name}: no draft attached "
                             "(attach_draft first)")
        k = self.spec_k
        S = self.max_slots
        last = _np.asarray(last_tokens, _np.int32).reshape(S)
        pos = _np.asarray(positions, _np.int32).reshape(S)
        if self.draft.scan_steps == k:
            # one scanned dispatch replaces the k-step host loop below,
            # bit-identically: done0 all-False with budgets k+1 and
            # eos -1 can never flip a slot's done mask, so every slot —
            # free ones included — advances (lt, pos) exactly as the
            # loop's unconditional ``lt, pv = nxt, pv + 1`` does.
            toks_ks, _ = self.draft.decode_burst(
                last, pos,
                budgets=_np.full(S, k + 1, _np.int32),
                eos_ids=_np.full(S, -1, _np.int32),
                active=_np.ones(S, bool))
            drafted = _np.ascontiguousarray(toks_ks.T)         # (S, k)
        else:
            drafted = _np.zeros((S, k), _np.int32)
            lt, pv = last, pos
            for j in range(k):
                nxt = _np.asarray(self.draft.decode(lt, pv),
                                  _np.int32).reshape(S)
                drafted[:, j] = nxt
                lt, pv = nxt, pv + 1
        toks = _np.concatenate([last[:, None], drafted], axis=1)
        out = self.verify(toks, pos)
        match = out[:, :k] == drafted                          # (S, k)
        accepted = _np.where(match.all(axis=1), k,
                             _np.argmin(match, axis=1))
        if self.paged or self.draft.paged:
            self._rollback_rejected(pos, accepted)
        return out, accepted

    def _rollback_rejected(self, base_positions, accepted) -> None:
        """Paged rollback after a verify: for every slot that rejected
        draft tokens, run the pool's COW guard over the dirty tail so
        the next dispatch's overwrites cannot touch a shared block.
        Block tables are per-slot operands, so a neighbor never observes
        another slot's rollback."""
        for s in range(self.max_slots):
            if int(accepted[s]) >= self.spec_k:
                continue
            keep = int(base_positions[s]) + int(accepted[s]) + 1
            for eng in (self, self.draft):
                if not eng.paged or not eng._slot_blocks[s]:
                    continue
                blocks = eng._slot_blocks[s]
                new = eng.pool.rewind(blocks, keep)
                if new != blocks:
                    eng._slot_blocks[s] = new
                    row = _np.zeros(eng.max_blocks_per_slot, _np.int32)
                    row[:len(new)] = new
                    eng._tables[s] = row
                    eng._tables_dev = None

    # -- paged-pool bookkeeping (no-ops in dense mode) -------------------
    def release_slot(self, slot: int) -> None:
        """Return ``slot``'s blocks to the pool (decref — shared prefix
        blocks stay live for their other readers / the prefix cache).
        Cascades to the draft engine's mirrored slot."""
        if self.draft is not None:
            self.draft.release_slot(slot)
        if not self.paged:
            return
        blocks = self._slot_blocks[int(slot)]
        if blocks:
            self.pool.release(blocks)
        self._slot_blocks[int(slot)] = []
        self._tables[int(slot)] = 0
        self._tables_dev = None

    def can_admit(self, tokens, reserve_tokens: int,
                  reserved_blocks: int = 0) -> bool:
        """Admission check: can the pool reserve worst-case capacity for
        this prompt right now?  ``reserved_blocks`` discounts capacity
        promised to earlier admits in the same scheduling step.  Dense
        mode always admits (capacity == slots there).  With a draft
        attached both pools must fit the reservation (plus the spec_k
        verify headroom)."""
        if self.draft is not None and not self.draft.can_admit(
                tokens, int(reserve_tokens) + self.spec_k,
                reserved_blocks):
            return False
        if not self.paged:
            return True
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        n = int(toks.shape[0])
        reserve = int(reserve_tokens) \
            + (self.spec_k if self.draft is not None else 0)
        reserve = max(n + 1, min(reserve, self.max_len))
        return self.pool.can_admit(toks, n, reserve, reserved_blocks)

    def reserve_estimate(self, reserve_tokens: int) -> int:
        """Worst-case blocks a request reserving ``reserve_tokens``
        positions can take (no sharing assumed) — the scheduler's
        discount unit for multi-admit steps."""
        if not self.paged:
            return 0
        from .kvcache import blocks_for
        reserve = int(reserve_tokens) \
            + (self.spec_k if self.draft is not None else 0)
        return blocks_for(min(reserve, self.max_len), self.block_size)

    def kv_capacity_tokens(self) -> int:
        """Total token positions the KV cache can hold across all
        requests — the backpressure unit for admission control."""
        if self.paged:
            return (self.num_blocks - 1) * self.block_size
        return self.max_slots * self.max_len

    def kv_stats(self) -> dict:
        """Cache-utilization facts for ``GET /v1/models`` and
        ``stats()``."""
        if not self.paged:
            return {"kv_paged": False,
                    "kv_capacity_tokens": self.kv_capacity_tokens()}
        out = {"kv_paged": True,
               "kv_capacity_tokens": self.kv_capacity_tokens()}
        out.update(self.pool.stats())
        return out

    def slot_occupancy(self) -> List[dict]:
        """Per-slot KV occupancy (paged mode; ``[]`` dense): blocks held
        and reserved token capacity per live slot — the flight-dump view
        of who holds the pool when an OOM hits."""
        if not self.paged:
            return []
        out = []
        for slot, blocks in enumerate(self._slot_blocks):
            if blocks:
                out.append({"slot": slot, "blocks": len(blocks),
                            "reserved_tokens":
                                len(blocks) * self.block_size})
        return out

    def program_inventory(self) -> dict:
        """Runtime program-set inventory (``GET /programs``, merged into
        ``/v1/models``, woven into flight dumps): the closed-set
        accounting (expected vs AOT-compiled programs) next to the
        per-program dispatch-ledger rows — what actually ran, how often,
        how long ago — plus per-slot KV occupancy.  Recurses into an
        attached draft engine."""
        prefix = "serving:" + self.name + ":"
        inv = {
            "model": self.name,
            "expected_programs": self.expected_programs,
            "compiled_programs": self.compiled_programs(),
            "warm": self.warm,
            "paged": self.paged,
            "scan_steps": self.scan_steps,
            "spec_k": self.spec_k if self.draft is not None else 0,
            "programs": _telemetry.dispatch_ledger(prefix=prefix),
            "slots": self.slot_occupancy(),
        }
        if self.draft is not None:
            inv["draft"] = self.draft.program_inventory()
        return inv

    # -- warmup / introspection -----------------------------------------
    @property
    def expected_programs(self) -> int:
        """Size of the CLOSED program set: one prefill per bucket (plus
        one suffix-prefill per bucket when the prefix cache can hit),
        ONE decode, ONE decode burst (when ``scan_steps >= 1`` — the
        scan length is baked, budgets/eos/done are operands, so one
        program serves every k-step burst), and — with a draft attached
        — ONE verify (the query width is baked from ``spec_k``, so no
        per-accept-length programs exist)."""
        per_bucket = 2 if self.prefix_cache_enabled else 1
        return per_bucket * len(self.prefill_buckets) + 1 \
            + (1 if self.scan_steps >= 1 else 0) \
            + (1 if self.draft is not None else 0)

    def warmup(self) -> int:
        """AOT-compile the whole closed program set — every prefill
        bucket (miss AND, with the prefix cache on, suffix/hit variants)
        plus THE decode program — then reset the cache (warmup traffic
        must not look like live slots or poison the prefix cache).
        Returns the number of programs warmed."""
        import jax.numpy as jnp
        self._warming = True
        try:
            for b in self.prefill_buckets:
                self.prefill(_np.zeros(max(1, min(b, self.max_len - 1)),
                                       _np.int32), 0)
                self.release_slot(0)
            if self.paged and self.prefix_cache_enabled:
                # suffix programs take ctx/table as OPERANDS: one dummy
                # dispatch per bucket (writes land in the null block)
                row = jnp.zeros(self.max_blocks_per_slot, jnp.int32)
                for b in self.prefill_buckets:
                    sn = max(1, min(b, self.max_len - 1))
                    self._unpack_prefill(self._guarded(
                        self._prefill_ext,
                        jnp.zeros((1, b), jnp.int32),
                        jnp.asarray(sn, jnp.int32),
                        jnp.asarray(0, jnp.int32), row,
                        self._slot_samp(0)))
            self.decode(_np.zeros(self.max_slots, _np.int32),
                        _np.zeros(self.max_slots, _np.int32))
            if self.scan_steps >= 1:
                # budgets of 1 exercise the in-program done path; the
                # post-warmup reset wipes whatever the burst wrote
                self.decode_burst(
                    _np.zeros(self.max_slots, _np.int32),
                    _np.zeros(self.max_slots, _np.int32),
                    _np.ones(self.max_slots, _np.int32),
                    _np.full(self.max_slots, -1, _np.int32),
                    _np.ones(self.max_slots, bool))
            if self.draft is not None:
                self.verify(
                    _np.zeros((self.max_slots, self.spec_k + 1),
                              _np.int32),
                    _np.zeros(self.max_slots, _np.int32))
        finally:
            self._warming = False
        self.reset()
        if self.draft is not None:
            self.draft.warmup()
        # closed-set accounting must balance HERE, loudly: a warmup that
        # compiled more programs than expected_programs predicts means
        # the program set is not closed (a per-request shape leaked into
        # a trace); fewer means the inventory over-promises and the
        # readiness gate would wait forever on real cache misses.
        compiled = self.compiled_programs()
        if compiled and compiled != self.expected_programs:
            raise MXNetError(
                f"{self.name}: program accounting drift after warmup — "
                f"compiled {compiled} programs, expected "
                f"{self.expected_programs} (closed program set violated)")
        self._warmup_done = True
        return self.expected_programs

    def compiled_programs(self) -> int:
        try:
            n = int(self._prefill_jit._cache_size()) \
                + int(self._decode_jit._cache_size()) \
                + int(self._decode_burst_jit._cache_size()) \
                + int(self._verify_jit._cache_size())
            if self._prefill_ext_jit is not None:
                n += int(self._prefill_ext_jit._cache_size())
            return n
        except Exception:
            return 0

    @property
    def warm(self) -> bool:
        if self._warmup_done:
            return True
        return self.compiled_programs() >= self.expected_programs

    # -- reference path --------------------------------------------------
    def generate(self, tokens, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None,
                 speculative: Optional[bool] = None,
                 sampling=None):
        """Solo generation through the SERVING programs (slot 0) — the
        engine-level convenience used by tests and the bench; the
        continuous batcher drives the same programs for many slots.
        With a draft attached the speculative step loop is the default
        (``speculative=False`` forces plain decode); every emitted token
        is a target sample either way, so the outputs are identical.
        ``sampling`` is an optional :class:`~.sampling.SamplingParams`
        (None: greedy) installed into slot 0 for the run."""
        toks = list(_np.asarray(tokens, _np.int32).reshape(-1))
        n = len(toks)
        budget = min(int(max_new_tokens), self.max_len - n)
        if budget < 1:
            raise MXNetError(
                f"{self.name}: no token budget (prompt {n}, max_len "
                f"{self.max_len})")
        spec = self.draft is not None if speculative is None \
            else bool(speculative) and self.draft is not None
        self.set_slot_sampling(0, sampling)
        out = [self.prefill(toks, 0, reserve_tokens=n + budget)]
        try:
            lt = _np.zeros(self.max_slots, _np.int32)
            pv = _np.zeros(self.max_slots, _np.int32)
            while len(out) < budget and (eos_id is None
                                         or out[-1] != int(eos_id)):
                lt[0] = out[-1]
                pv[0] = n + len(out) - 1
                if spec:
                    burst, acc = self.spec_step(lt, pv)
                    for j in range(int(acc[0]) + 1):
                        out.append(int(burst[0, j]))
                        if len(out) >= budget or (
                                eos_id is not None
                                and out[-1] == int(eos_id)):
                            break
                else:
                    nxt = self.decode(lt, pv)
                    out.append(int(nxt[0]))
        finally:
            self.release_slot(0)
        return out

    def __repr__(self):
        return (f"<GenerationEngine {self.name!r}: slots={self.max_slots}, "
                f"max_len={self.max_len}, layers={self.num_layers}, "
                f"heads={self.num_heads}, "
                f"prefill_buckets={list(self.prefill_buckets)}, "
                f"programs={self.compiled_programs()}>")
