"""DynamicBatcher — coalesce concurrent inference requests into one
compiled dispatch.

Requests land in a bounded FIFO queue; a single worker thread pops the
head and keeps gathering compatible requests (same per-example shapes
and dtypes — FIFO order is never reordered past an incompatible head)
until the group reaches ``max_batch_size`` rows or the head request's
``max_delay_ms`` deadline expires.  The group is concatenated along the
batch axis, padded up to the engine's next bucket, dispatched as ONE
compiled program, and the output rows are scattered back to the waiting
callers.

Operational behavior is wired into the runtime's existing planes:

* **backpressure** — a full queue rejects immediately with
  :class:`QueueFullError` (``mxtpu_serve_rejected``); the client sees a
  429 from the HTTP front-end instead of unbounded latency.
* **faults** — ``serving.queue`` is polled at submit and
  ``serving.infer`` inside the batched dispatch (``MXNET_FAULT_PLAN``
  site grammar, docs/robustness.md).  A failed batch dispatch retries
  under :func:`fault.retry_call`; on exhaustion the batcher publishes a
  ``fallback`` FAULT event, bumps ``mxtpu_serve_fallbacks``, and
  executes each request individually so one poisoned batch cannot fail
  every rider.
* **graceful drain** — :meth:`close` stops intake, lets the worker
  drain everything already queued (coalescing without waiting out the
  delay deadline), then joins the worker.
* **telemetry** — ``serve.request`` (submit-to-result) and
  ``serve.batch`` spans, queue-wait / batch-size / end-to-end latency
  histograms, per-model queue-depth gauge.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence

from ..base import MXNetError, getenv, getenv_int
from ..ndarray.ndarray import NDArray
from .. import fault as _fault
from .. import telemetry as _telemetry
from . import metrics as _m

__all__ = ["DynamicBatcher", "QueueFullError"]


class QueueFullError(MXNetError):
    """The batcher's bounded queue is full — backpressure, not failure."""


class _Request:
    """One submitted batch: arrays + a latch the caller waits on."""

    __slots__ = ("arrays", "n", "sig", "event", "outputs", "error",
                 "t_submit")

    def __init__(self, arrays, n, sig):
        self.arrays = arrays
        self.n = n
        self.sig = sig
        self.event = threading.Event()
        self.outputs = None
        self.error = None
        self.t_submit = time.monotonic()

    def result(self, timeout: Optional[float] = None) -> List:
        """Block for the scattered outputs; re-raises dispatch errors."""
        if not self.event.wait(timeout):
            raise TimeoutError("inference request timed out")
        if self.error is not None:
            raise self.error
        return self.outputs


class DynamicBatcher:
    """Batch-coalescing front-end over one :class:`InferenceEngine`.

    Defaults come from the serving env knobs (``MXNET_SERVE_MAX_BATCH``
    = 32, ``MXNET_SERVE_MAX_DELAY_MS`` = 5.0, ``MXNET_SERVE_QUEUE`` =
    128; docs/env_var.md)."""

    def __init__(self, engine, *, max_batch_size: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 queue_size: Optional[int] = None,
                 name: Optional[str] = None, retry_policy=None):
        self.engine = engine
        self.name = str(name or engine.name)
        if max_batch_size is None:
            max_batch_size = getenv_int("MXNET_SERVE_MAX_BATCH", 32)
        if engine.max_batch_size:
            max_batch_size = min(int(max_batch_size),
                                 int(engine.max_batch_size))
        self.max_batch_size = max(1, int(max_batch_size))
        if max_delay_ms is None:
            max_delay_ms = float(getenv("MXNET_SERVE_MAX_DELAY_MS", 5.0))
        self.max_delay = max(0.0, float(max_delay_ms)) / 1000.0
        if queue_size is None:
            queue_size = getenv_int("MXNET_SERVE_QUEUE", 128)
        self.queue_size = max(1, int(queue_size))
        self.retry_policy = retry_policy
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name=f"mxtpu-serve-{self.name}",
            daemon=True)
        self._thread.start()

    # -- submit ---------------------------------------------------------
    @staticmethod
    def _signature(arrays):
        return tuple((tuple(a.shape[1:]), str(getattr(a, "dtype", "?")))
                     for a in arrays)

    def submit_async(self, arrays: Sequence) -> _Request:
        """Enqueue one request batch; returns a latch whose
        ``result()`` blocks for the outputs.  Raises
        :class:`QueueFullError` under backpressure and ``MXNetError``
        after :meth:`close`."""
        _fault.inject("serving.queue")
        arrays = list(arrays)
        n = int(arrays[0].shape[0])
        req = _Request(arrays, n, self._signature(arrays))
        with self._cv:
            if self._closed:
                raise MXNetError(f"batcher {self.name!r} is closed")
            if len(self._queue) >= self.queue_size:
                _m.REJECTED.inc(model=self.name)
                raise QueueFullError(
                    f"{self.name}: queue full ({self.queue_size} "
                    "pending) — backpressure")
            self._queue.append(req)
            _m.QUEUE_DEPTH.set(len(self._queue), model=self.name)
            self._cv.notify_all()
        _m.REQUESTS.inc(model=self.name)
        return req

    def submit(self, arrays: Sequence,
               timeout: Optional[float] = None) -> List:
        """Synchronous request: enqueue, wait, return per-row outputs
        (jax arrays, sliced to this request's rows)."""
        with _telemetry.trace_span("serve.request", cat="serving",
                                   model=self.name):
            return self.submit_async(arrays).result(timeout)

    # -- worker ---------------------------------------------------------
    def _worker(self):
        while True:
            group = self._gather()
            if group is None:
                return
            self._run_group(group)

    def _gather(self):
        """Block for the head request, then coalesce until the batch is
        full, the head's delay deadline passes, or the next queued
        request is shape-incompatible (FIFO preserved).  Returns None
        when closed and drained."""
        with self._cv:
            while not self._queue:
                if self._closed:
                    return None
                self._cv.wait(0.05)
            head = self._queue.popleft()
            group, total = [head], head.n
            deadline = time.monotonic() + self.max_delay
            while total < self.max_batch_size:
                if self._queue:
                    nxt = self._queue[0]
                    if nxt.sig != head.sig \
                            or total + nxt.n > self.max_batch_size:
                        break
                    group.append(self._queue.popleft())
                    total += nxt.n
                    continue
                if self._closed:        # drain fast: no deadline wait
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            _m.QUEUE_DEPTH.set(len(self._queue), model=self.name)
        return group

    def _run_group(self, group):
        import jax.numpy as jnp
        t0 = time.monotonic()
        for r in group:
            _m.QUEUE_WAIT.observe(t0 - r.t_submit)
        total = sum(r.n for r in group)
        _m.BATCH_SIZE.observe(total)
        _m.BATCHES.inc(model=self.name)
        with _telemetry.trace_span("serve.batch", cat="serving",
                                   model=self.name,
                                   requests=len(group), rows=total):
            try:
                def _val(a):
                    return a._data if isinstance(a, NDArray) \
                        else jnp.asarray(a)
                if len(group) == 1:
                    ins = group[0].arrays
                else:
                    ins = [jnp.concatenate(
                        [_val(r.arrays[i]) for r in group], axis=0)
                        for i in range(len(group[0].arrays))]

                def run():
                    _fault.inject("serving.infer")
                    return self.engine.predict(ins)

                try:
                    outs = _fault.retry_call(run, site="serving.infer",
                                             policy=self.retry_policy)
                except Exception as e:
                    self._fallback(group, e)
                    return
                off = 0
                for r in group:
                    r.outputs = [o[off:off + r.n] for o in outs]
                    off += r.n
            except Exception as e:      # worker must survive anything
                for r in group:
                    r.error = e
            finally:
                done = time.monotonic()
                for r in group:
                    _m.LATENCY.observe(done - r.t_submit)
                    r.event.set()

    def _fallback(self, group, err):
        """Batched dispatch failed after retries: run each request on
        its own so one poisoned batch can't fail every rider.  Singles
        bypass the ``serving.infer`` fault site — the plan already fired
        on the batch attempts."""
        _telemetry.FAULT.publish(site="serving.infer", event="fallback",
                                 kind=type(err).__name__,
                                 requests=len(group))
        _m.FALLBACKS.inc(model=self.name)
        for r in group:
            try:
                r.outputs = self.engine.predict(r.arrays)
            except Exception as e:
                r.error = e

    # -- lifecycle ------------------------------------------------------
    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake.  ``drain=True`` (default) lets the worker finish
        everything already queued; ``drain=False`` fails pending
        requests immediately.  Idempotent."""
        with self._cv:
            self._closed = True
            dropped = []
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
            self._cv.notify_all()
        for r in dropped:
            r.error = MXNetError(f"batcher {self.name!r} closed")
            r.event.set()
        self._thread.join(timeout=timeout)
        with self._cv:
            _m.QUEUE_DEPTH.set(0, model=self.name)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        with self._cv:
            depth = len(self._queue)
        return {"model": self.name, "queue_depth": depth,
                "queue_size": self.queue_size,
                "max_batch_size": self.max_batch_size,
                "max_delay_ms": self.max_delay * 1000.0,
                "closed": self._closed,
                "buckets": list(self.engine.buckets),
                "compiled_programs": self.engine.compiled_programs()}
